"""Sync DiLoCo on nanoGPT — H local steps, then one outer reduce.

Reference parity: /root/reference/python/examples/nanogpt_diloco/
sync_diloco.py (torch inner AdamW + outer Nesterov SGD on pseudo-gradients,
shared-state revision per outer step, late joiners catch up via
sync_shared_state). TPU-first: the inner loop is a jitted SPMD step over the
local mesh (pccl_tpu.parallel.train); only one flat fp32 pseudo-gradient
vector crosses the ring per outer step, optionally quantized.

Run (2 peers):
    python -m pccl_tpu.comm.master --port 48500 &
    python examples/nanogpt_diloco/sync_diloco.py --master-port 48500 \
        --base-port 56000 --min-world 2 &
    python examples/nanogpt_diloco/sync_diloco.py --master-port 48500 \
        --base-port 56100 --min-world 2
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
sys.path.insert(0, str(Path(__file__).resolve().parent.parent.parent))


import common


def main() -> int:
    ap = argparse.ArgumentParser()
    common.add_comm_args(ap)
    ap.add_argument("--outer-steps", type=int, default=8)
    ap.add_argument("--inner-steps", type=int, default=10)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--block", type=int, default=64)
    ap.add_argument("--inner-lr", type=float, default=1e-3)
    common.add_lr_schedule_args(ap)
    common.add_data_args(ap)
    ap.add_argument("--outer-lr", type=float, default=0.7)
    ap.add_argument("--quantize", choices=["none", "minmax"], default="none")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--shm-staging", action="store_true",
                    help="stage pseudo-gradients in a registered shm buffer "
                         "(zero-copy ring when peers share this host)")
    ap.add_argument("--checkpoint-dir", default=None,
                    help="save outer state here every --checkpoint-every "
                         "steps and resume from the newest snapshot")
    ap.add_argument("--checkpoint-every", default=10,
                    type=lambda v: max(1, int(v)))
    common.add_model_args(ap)
    args = ap.parse_args()

    common.force_cpu_if_requested()
    import jax
    import jax.numpy as jnp

    from pccl_tpu.comm import DataType
    from pccl_tpu.parallel import mesh as mesh_lib, train as train_lib
    from pccl_tpu.parallel.diloco import Diloco, DilocoConfig

    comm = common.connect(args)

    mesh = mesh_lib.make_mesh(jax.devices(), ("dp", "tp"))
    cfg = common.model_config(args, char_level=args.data == "text")
    schedule = common.make_schedule(
        args, args.inner_lr, args.outer_steps * args.inner_steps)
    params, tx, opt_state = train_lib.make_train_state(
        jax.random.PRNGKey(args.seed), cfg, mesh, lr=args.inner_lr,
        schedule=schedule)
    step_fn = train_lib.build_train_step(cfg, tx, mesh)
    data_sharding = mesh_lib.batch_sharding(mesh)

    dl = Diloco(comm, params,
                DilocoConfig(inner_steps=args.inner_steps,
                             outer_lr=args.outer_lr,
                             quantization=common.quant_from_arg(args.quantize),
                             quantized_dtype=DataType.UINT8,
                             shm_staging=args.shm_staging))

    from pccl_tpu.utils.profiler import Profiler

    ckpt = start = None
    if args.checkpoint_dir:
        from pccl_tpu.utils.checkpoint import DilocoCheckpoint

        ckpt = DilocoCheckpoint(args.checkpoint_dir)
        start = ckpt.maybe_restore(dl)
        if start:
            # continue INNER training from the restored outer params —
            # training from seed-init params would make the first
            # pseudo-gradient (outer − inner) a restored-vs-seed jump
            # that the outer SGD then applies toward the seed
            params = dl.params()
            if schedule is not None:
                # the schedule's position lives in the optimizer's step
                # count, which resumes at 0 — shift it so the decay
                # continues where the run left off instead of re-running
                # warmup (inner Adam moments restart fresh by design:
                # DiLoCo shares only the outer state)
                shifted = common.make_schedule(
                    args, args.inner_lr,
                    args.outer_steps * args.inner_steps,
                    offset=start * args.inner_steps)
                _, tx, opt_state = train_lib.make_train_state(
                    jax.random.PRNGKey(args.seed), cfg, mesh,
                    lr=args.inner_lr, schedule=shifted)
                step_fn = train_lib.build_train_step(cfg, tx, mesh)
            print(f"resumed from outer step {start}", flush=True)

    prof = Profiler(enabled=args.profile or bool(args.trace_out))
    next_batch = common.make_batch_fn(args, cfg.vocab_size)
    if start:
        # fast-forward the deterministic data stream past the batches outer
        # steps [0, start) already consumed — without this a resumed run
        # retrains the replayed prefix (train_ddp.py's resume path drains
        # its stream the same way)
        for _ in range(start * args.inner_steps):
            next_batch()
    first_loss = last_loss = None
    for outer in range(start or 0, args.outer_steps):
        common.admit_pending(comm)
        with prof.section("inner"):
            for _ in range(args.inner_steps):
                tok, tgt = next_batch()
                tok = jax.device_put(jnp.asarray(tok), data_sharding)
                tgt = jax.device_put(jnp.asarray(tgt), data_sharding)
                params, opt_state, loss = step_fn(params, opt_state, tok, tgt)
        with prof.section("outer/ring+sgd"):
            params = dl.outer_step(params)  # ring AVG of pseudo-grads + SGD
        loss = float(loss)
        first_loss = first_loss if first_loss is not None else loss
        last_loss = loss
        world = comm.world_size if comm is not None else 1
        print(f"outer {outer} loss {loss:.4f} world {world} "
              f"revision {dl.step}", flush=True)
        if ckpt is not None and (outer + 1) % args.checkpoint_every == 0:
            ckpt.save(dl)

    common.finish_profile(args, prof)
    return common.report_final(first_loss, last_loss, comm)


if __name__ == "__main__":
    sys.exit(main())
