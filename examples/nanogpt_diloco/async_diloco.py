"""Async DiLoCo on nanoGPT — the outer reduce overlaps the next inner phase.

Reference parity: /root/reference/python/examples/nanogpt_diloco/
async_diloco.py and docs/md/07-.../03-AsyncDiloco.md — the reduce of outer
step t runs on a background thread while inner steps of t+1 compute; the
delayed update lands at the next outer boundary (one-step-delayed
pseudo-gradients). TPU angle: the inner phase keeps the chips busy the whole
time — the WAN hop is fully hidden behind jitted SPMD compute.

Run: same as sync_diloco.py, swapping the script name.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
sys.path.insert(0, str(Path(__file__).resolve().parent.parent.parent))


import common


def main() -> int:
    ap = argparse.ArgumentParser()
    common.add_comm_args(ap)
    ap.add_argument("--outer-steps", type=int, default=10)
    ap.add_argument("--inner-steps", type=int, default=10)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--block", type=int, default=64)
    ap.add_argument("--inner-lr", type=float, default=1e-3)
    common.add_lr_schedule_args(ap)
    common.add_data_args(ap)
    ap.add_argument("--outer-lr", type=float, default=0.7)
    ap.add_argument("--quantize", choices=["none", "minmax"], default="none")
    ap.add_argument("--seed", type=int, default=0)
    common.add_model_args(ap)
    args = ap.parse_args()

    common.force_cpu_if_requested()
    import jax
    import jax.numpy as jnp

    from pccl_tpu.comm import DataType
    from pccl_tpu.parallel import mesh as mesh_lib, train as train_lib
    from pccl_tpu.parallel.diloco import AsyncDiloco, DilocoConfig

    comm = common.connect(args)

    mesh = mesh_lib.make_mesh(jax.devices(), ("dp", "tp"))
    cfg = common.model_config(args, char_level=args.data == "text")
    schedule = common.make_schedule(
        args, args.inner_lr, args.outer_steps * args.inner_steps)
    params, tx, opt_state = train_lib.make_train_state(
        jax.random.PRNGKey(args.seed), cfg, mesh, lr=args.inner_lr,
        schedule=schedule)
    step_fn = train_lib.build_train_step(cfg, tx, mesh)
    data_sharding = mesh_lib.batch_sharding(mesh)

    # delayed gradients oscillate with heavy momentum; reference async runs
    # tame the outer momentum (docs/md/07-.../03-AsyncDiloco.md)
    dl = AsyncDiloco(comm, params,
                     DilocoConfig(inner_steps=args.inner_steps,
                                  outer_lr=args.outer_lr, outer_momentum=0.0,
                                  quantization=common.quant_from_arg(args.quantize),
                                  quantized_dtype=DataType.UINT8))

    from pccl_tpu.utils.profiler import Profiler

    prof = Profiler(enabled=args.profile or bool(args.trace_out))
    next_batch = common.make_batch_fn(args, cfg.vocab_size)
    first_loss = last_loss = None
    for outer in range(args.outer_steps):
        common.admit_pending(comm)
        with prof.section("inner"):
            for _ in range(args.inner_steps):
                tok, tgt = next_batch()
                tok = jax.device_put(jnp.asarray(tok), data_sharding)
                tgt = jax.device_put(jnp.asarray(tgt), data_sharding)
                params, opt_state, loss = step_fn(params, opt_state, tok, tgt)
        with prof.section("outer/launch+join_prev"):
            # kicks the ring reduce on a background thread; returns
            # immediately (the section times joining the PREVIOUS reduce)
            params = dl.outer_step_async(params)
        loss = float(loss)
        first_loss = first_loss if first_loss is not None else loss
        last_loss = loss
        world = comm.world_size if comm is not None else 1
        print(f"outer {outer} loss {loss:.4f} world {world}", flush=True)
    params = dl.finish()  # land the last in-flight reduce

    common.finish_profile(args, prof)
    return common.report_final(first_loss, last_loss, comm)


if __name__ == "__main__":
    sys.exit(main())
