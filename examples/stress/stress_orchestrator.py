"""Fault-tolerance soak test: churn real peer processes until the clock runs out.

Reference parity: /root/reference/python/tests/stress_tests/basic_stress_test/
stresstest_orchestrator.py — launch a master + N peers on loopback, let peers
randomly kill themselves mid-run (tests/ft_peer.py --die-prob), relaunch
them, and watch stdout heartbeats with a stall detector. Progress anywhere
in the group within the stall window = healthy; no progress = the collective
runtime wedged and the soak FAILS.

Master churn: with --master-kill-interval > 0 the MASTER process is also
SIGKILLed on a schedule and restarted on the same port (reference recipe:
docs/md/05-ImplementationNotes/03_MasterOrchestration.md — restart the
master, peers reconnect, training resumes). Without a journal, peers rejoin
with fresh communicators (tests/ft_peer.py rejoin path); with --journal PATH
the restarted master rehydrates its state from the write-ahead journal and
peers SESSION-RESUME under their old UUIDs instead — a master restart is a
blip, not a world reset (docs/10_high_availability.md). The run summary
prints measured master downtime plus resumes-vs-full-rejoins counts, so a
journaled run can be eyeballed for "all resumes, zero rejoins".

Usage:
    python examples/stress/stress_orchestrator.py --duration 120 --peers 3
    python examples/stress/stress_orchestrator.py --duration 120 --peers 3 \
        --master-kill-interval 30
    python examples/stress/stress_orchestrator.py --duration 120 --peers 3 \
        --master-kill-interval 30 --journal /tmp/master.journal
"""

from __future__ import annotations

import argparse
import socket
import subprocess
import sys
import threading
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent.parent
PEER = REPO / "tests" / "ft_peer.py"
sys.path.insert(0, str(REPO))


class MasterProc:
    """The master as a killable subprocess (python -m pccl_tpu.comm.master)."""

    _instance = 0

    def __init__(self, port: int, journal: str | None = None,
                 metrics_port: int | None = None):
        self.port = port
        import os
        MasterProc._instance += 1
        log = os.environ.get("MASTER_LOG")
        out = (open(f"{log}.{MasterProc._instance}", "wb")
               if log else subprocess.DEVNULL)
        cmd = [sys.executable, "-m", "pccl_tpu.comm.master", "--port", str(port)]
        if journal:
            cmd += ["--journal", journal]
        if metrics_port is not None:
            cmd += ["--metrics-port", str(metrics_port)]
        self.proc = subprocess.Popen(
            cmd, cwd=str(REPO), stdout=out, stderr=subprocess.STDOUT)
        deadline = time.time() + 15
        while time.time() < deadline:
            try:
                with socket.create_connection(("127.0.0.1", port), timeout=1):
                    return
            except OSError:
                if self.proc.poll() is not None:
                    raise RuntimeError("master process died on startup")
                time.sleep(0.1)
        raise RuntimeError("master never started listening")

    def alive(self) -> bool:
        return self.proc.poll() is None

    def kill(self) -> None:
        if self.alive():
            self.proc.kill()
        self.proc.wait(timeout=10)


class Peer:
    def __init__(self, master_port: int, idx: int, base_port: int,
                 die_prob: float, seed: int, env: dict | None = None,
                 count: int = 4096, extra_args: list | None = None):
        self.idx = idx
        self.base_port = base_port
        cmd = [sys.executable, str(PEER), "--master-port", str(master_port),
               "--rank", str(idx), "--base-port", str(base_port),
               "--steps", "1000000", "--min-world", "2",
               "--step-interval", "0.05", "--count", str(count),
               "--die-prob", str(die_prob), "--seed", str(seed)]
        cmd += extra_args or []
        if env:
            cmd += ["--stats-every", "10"]
        import os
        penv = {**os.environ, **(env or {})}
        self.proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                                     stderr=subprocess.STDOUT, text=True,
                                     env=penv)
        self.stats: dict = {}  # newest STATS snapshot (chaos runs)
        self.steps = 0
        self.wrong_sync = 0  # bit-wrong shared-state adoptions (gate: 0)
        self.resumes = 0  # total session resumes across this peer's comm lives
        self.rejoins = 0  # full re-registrations (fresh communicator)
        # RESUMED total=N is per-COMMUNICATOR and resets to 0 on a rejoin, so
        # fold each comm life's max into a base when a REJOIN line arrives
        self._resume_base = 0
        self._life_max = 0
        self._t = threading.Thread(target=self._pump, daemon=True)
        self._t.start()

    def _pump(self) -> None:
        assert self.proc.stdout is not None
        for line in self.proc.stdout:
            if line.startswith("STEP "):
                self.steps += 1
            elif line.startswith("WRONG SYNC"):
                self.wrong_sync += 1
                print(f"peer {self.idx}: {line.rstrip()}", flush=True)
            elif line.startswith("STATS "):
                try:
                    import json
                    self.stats = json.loads(line[6:])
                except ValueError:
                    pass
            elif line.startswith("INJECT"):
                # surface the victim's chaos injection (or its failure)
                print(f"peer {self.idx}: {line.rstrip()}", flush=True)
            elif line.startswith("RESUMED total="):
                try:
                    n = int(line.split("total=")[1].split()[0])
                except (ValueError, IndexError):
                    continue
                self._life_max = max(self._life_max, n)
                self.resumes = self._resume_base + self._life_max
            elif line.startswith("REJOIN"):
                self.rejoins += 1
                self._resume_base += self._life_max
                self._life_max = 0
                self.resumes = self._resume_base

    def alive(self) -> bool:
        return self.proc.poll() is None

    def kill(self) -> None:
        if self.alive():
            self.proc.kill()
        self.proc.wait(timeout=10)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--duration", type=float, default=120.0)
    ap.add_argument("--peers", type=int, default=3)
    ap.add_argument("--die-prob", type=float, default=0.002)
    ap.add_argument("--master-port", type=int, default=48900)
    ap.add_argument("--base-port", type=int, default=58000)
    ap.add_argument("--master-kill-interval", type=float, default=0.0,
                    help="SIGKILL + restart the master every this many "
                         "seconds (0 = master never dies)")
    ap.add_argument("--master-down-time", type=float, default=1.5,
                    help="how long the master stays dead before restart")
    ap.add_argument("--journal", default=None, metavar="PATH",
                    help="master HA journal: restarts rehydrate state and "
                         "peers session-resume instead of rejoining")
    ap.add_argument("--stall-seconds", type=float, default=120.0,
                    help="fail if NO peer makes progress for this long "
                         "(reference uses 5 minutes)")
    ap.add_argument("--metrics-port", type=int, default=None,
                    help="observability plane: master serves /metrics + "
                         "/health here, peers push telemetry digests, and "
                         "the exit summary prints the fleet health view "
                         "(docs/09_observability.md)")
    ap.add_argument("--telemetry-push-ms", type=int, default=250,
                    help="digest cadence for the peers when --metrics-port "
                         "is set")
    ap.add_argument("--chaos", default=None, metavar="SCHEDULE",
                    help="scripted fault injection (docs/05): the victim "
                         "sender (peer 1) injects this chaos schedule "
                         "(e.g. 'flap@t=10s:200msx5;degrade@t=20s:"
                         "20mbit/15s') on its OUTBOUND ring edge mid-run "
                         "(self-discovered from stats, ring-order-proof); "
                         "the edge watchdog + window failover turn on "
                         "fleet-wide and a CHAOS SUMMARY exit line prints. "
                         "A raw 'endpoint=schedule,...' map is applied "
                         "verbatim via PCCLT_WIRE_CHAOS_MAP instead.")
    ap.add_argument("--chaos-mbps", type=float, default=300.0,
                    help="baseline emulated per-edge bandwidth for chaos "
                         "runs (per-endpoint netem edges must exist for "
                         "the schedule to retune)")
    ap.add_argument("--count", type=int, default=4096,
                    help="per-step all-reduce element count (chaos runs "
                         "want real payloads so windows exist to fail over)")
    ap.add_argument("--sync-churn", type=int, default=0, metavar="ELEMS",
                    help="churn-proof shared-state lane (docs/04): every "
                         "peer syncs an ELEMS-float32 state per step over "
                         "the content-addressed chunk plane; the schedule "
                         "adds a JOINER FLOOD (half the peers SIGKILLed at "
                         "once at 1/3 duration, relaunched as cold "
                         "joiners) and a busiest-SEEDER kill at 2/3 "
                         "duration (picked by ss_seeder_chunks_served). "
                         "Exit prints a SYNC SUMMARY with gates: zero "
                         "wrong-content adoptions, zero failed syncs on "
                         "live peers.")
    ap.add_argument("--fleet-scale", type=int, default=0, metavar="N",
                    help="fleet-scale observability lane (docs/09): a "
                         "native digest bot keeps N simulated OBSERVER "
                         "sessions (PCCP/2 hello tail byte; they push "
                         "telemetry, never join the world) flooding the "
                         "master for the whole soak while the real peers "
                         "churn. Exit prints a FLEET SCALE summary with "
                         "digests pushed, ingest-queue drops, and a "
                         "promlint verdict on the final /metrics scrape. "
                         "Requires --metrics-port.")
    ap.add_argument("--fleet-edges", type=int, default=8,
                    help="edges per simulated observer for --fleet-scale")
    ap.add_argument("--fleet-hz", type=float, default=5.0,
                    help="digest cadence per simulated observer for "
                         "--fleet-scale")
    ap.add_argument("--sync-chunk-bytes", type=int, default=262144,
                    help="PCCLT_SS_CHUNK_BYTES for --sync-churn peers")
    ap.add_argument("--sync-mbps", type=float, default=250.0,
                    help="per-process wildcard egress pacing for "
                         "--sync-churn (models a per-NIC bottleneck so "
                         "multi-source fetch genuinely multiplies "
                         "bandwidth)")
    ap.add_argument("--sync-degrade-mbit", type=float, default=2.0,
                    help="--sync-churn schedules a seeder-edge DEGRADE to "
                         "this rate on peer 0's egress bucket from 1/3 "
                         "duration (the joiner flood) through the seeder "
                         "kill, so its serves toward recovering joiners "
                         "stall and the serve-side watchdog SUSPECT rung "
                         "fires in every soak, not only when a peer "
                         "happens to die mid-serve (0 = no degrade)")
    args = ap.parse_args()

    if args.metrics_port is not None:
        # peers inherit the cadence; the master flag rides the CLI
        import os
        os.environ["PCCLT_TELEMETRY_PUSH_MS"] = str(args.telemetry_push_ms)

    # chaos plane (docs/05): every peer gets a uniform emulated mesh + the
    # watchdog. Schedule mode: the victim SENDER (peer 1) injects the
    # schedule on its OUTBOUND ring edge at runtime, discovered from its
    # own stats() — the ATSP-adopted ring order decides who its successor
    # is, so a hardcoded edge could land on one the ring never uses. Its
    # failover then relays through a peer whose edges stay healthy. A raw
    # "endpoint=schedule" map is still applied verbatim via the env.
    chaos_env: dict[int, dict] = {}
    chaos_args: dict[int, list] = {}
    if args.chaos:
        p2p = {i: args.base_port + i * 16 for i in range(args.peers)}
        mbps_map = ",".join(f"127.0.0.1:{p}={args.chaos_mbps}"
                            for p in p2p.values())
        base = {"PCCLT_WIRE_MBPS_MAP": mbps_map, "PCCLT_WATCHDOG": "1"}
        for i in range(args.peers):
            chaos_env[i] = dict(base)
        raw_map = "=" in args.chaos.split("@", 1)[0]
        if raw_map:
            for i in range(args.peers):
                chaos_env[i]["PCCLT_WIRE_CHAOS_MAP"] = args.chaos
        elif args.peers >= 2:
            chaos_args[1] = ["--inject-spec", args.chaos, "--inject-at", "10"]

    # churn-sync lane env + per-peer args (docs/04)
    sync_args: list = []
    if args.sync_churn > 0:
        sync_args = ["--sync-state", str(args.sync_churn)]
        base_env = {"PCCLT_SS_CHUNK_BYTES": str(args.sync_chunk_bytes),
                    "PCCLT_WIRE_MBPS_MAP": f"127.0.0.1={args.sync_mbps}",
                    "PCCLT_WATCHDOG": "1"}
        for i in range(args.peers):
            chaos_env.setdefault(i, {}).update(base_env)
        if args.sync_degrade_mbit > 0:
            # scheduled seeder-edge degrade (docs/04): peer 0 — flood-proof,
            # so always up-to-date and in the seeder directory — has its
            # per-process egress bucket degraded from the joiner flood
            # (1/3 duration) through past the seeder kill. Its serves
            # toward the recovering joiners then stall past the watchdog
            # deadline and the SUSPECT rung fires in every soak; the
            # joiners' deadline re-source rescues the chunks from the
            # healthy seeders, so the round still completes.
            t0 = max(1, int(args.duration / 3))
            dur = max(8, int(args.duration / 2))
            spec = (f"127.0.0.1=degrade@t={t0}s:"
                    f"{args.sync_degrade_mbit:g}mbit/{dur}s")
            # a raw --chaos map owns the schedule; the degrade only rides
            # when the operator did not script their own
            chaos_env[0].setdefault("PCCLT_WIRE_CHAOS_MAP", spec)
            print(f"sync-churn: scheduled seeder-edge degrade on peer 0 "
                  f"({spec})", flush=True)
        for i in range(args.peers):
            chaos_args.setdefault(i, []).extend(sync_args)

    if args.fleet_scale > 0 and args.metrics_port is None:
        print("--fleet-scale requires --metrics-port (the summary gates on "
              "the scrape)", flush=True)
        return 2

    master = MasterProc(args.master_port, args.journal, args.metrics_port)

    # fleet-scale digest bot (docs/09): one daemon thread drives the native
    # flood in short rounds so a master assassination mid-soak just costs
    # one failed round — the next round's observers reconnect
    fleet_stop = threading.Event()
    fleet_sent = [0]
    fleet_failed_rounds = [0]

    def fleet_bot() -> None:
        import ctypes
        from pccl_tpu.comm import _native
        lib = _native.load()
        while not fleet_stop.is_set():
            sent = ctypes.c_uint64(0)
            wall = ctypes.c_double(0.0)
            rc = lib.pccltDigestFlood(
                b"127.0.0.1", args.master_port, args.fleet_scale,
                args.fleet_edges, args.fleet_hz, 5.0,
                min(8, max(1, args.fleet_scale // 64)),
                ctypes.byref(sent), ctypes.byref(wall))
            fleet_sent[0] += sent.value
            if rc != 0:
                fleet_failed_rounds[0] += 1
                time.sleep(1.0)  # master probably down; back off one beat

    fleet_thread = None
    if args.fleet_scale > 0:
        fleet_thread = threading.Thread(target=fleet_bot, daemon=True)
        fleet_thread.start()

    peers: list[Peer] = []
    seed = 1
    total_relaunches = 0
    master_restarts = 0
    master_downtime_s: list[float] = []  # SIGKILL -> listening again
    retired_steps = 0  # steps of peers that died; keeps the total monotone
    retired_resumes = 0
    retired_rejoins = 0
    next_master_kill = (time.time() + args.master_kill_interval
                        if args.master_kill_interval > 0 else None)
    # chaos accounting, folded across peer lives (relaunches reset stats)
    chaos_acc = {"faults_armed": 0, "faults_activated": 0, "failovers": 0,
                 "relays": 0, "relay_forwarded": 0, "dup_bytes": 0,
                 "suspects": 0, "confirms": 0, "aborted": 0}
    # churn-sync accounting (docs/04), folded the same way. Since the chunk
    # plane rides the pooled p2p conns, the per-edge stripe/watchdog/relay
    # counters now cover sync bytes too — fold them into the summary so the
    # CI lane can gate on "the hardened transport actually engaged".
    sync_acc = {"chunks_fetched": 0, "chunks_resourced": 0, "chunks_dup": 0,
                "promotions": 0, "seeder_deaths_survived": 0,
                "legacy_syncs": 0, "syncs_ok": 0, "syncs_failed": 0,
                "stripe_windows": 0, "stripe_bytes": 0, "suspects": 0,
                "relays": 0, "relay_bytes": 0, "aborted": 0}
    sync_events = {"floods": 0, "seeder_kills": 0, "wrong": 0}

    def fold_sync(stats: dict) -> None:
        c = stats.get("counters", {}) if stats else {}
        sync_acc["chunks_fetched"] += c.get("ss_chunks_fetched", 0)
        sync_acc["chunks_resourced"] += c.get("ss_chunks_resourced", 0)
        sync_acc["chunks_dup"] += c.get("ss_chunks_dup", 0)
        sync_acc["promotions"] += c.get("ss_seeder_promotions", 0)
        sync_acc["seeder_deaths_survived"] += c.get("ss_seeders_lost", 0)
        sync_acc["legacy_syncs"] += c.get("ss_legacy_syncs", 0)
        sync_acc["syncs_ok"] += c.get("syncs_ok", 0)
        sync_acc["syncs_failed"] += c.get("syncs_failed", 0)
        sync_acc["aborted"] += c.get("collectives_aborted", 0)
        for e in (stats.get("edges", {}) if stats else {}).values():
            sync_acc["stripe_windows"] += e.get("tx_stripe_windows", 0)
            sync_acc["stripe_bytes"] += e.get("tx_stripe_bytes", 0)
            sync_acc["suspects"] += e.get("wd_suspects", 0)
            sync_acc["relays"] += e.get("wd_relays", 0)
            sync_acc["relay_bytes"] += e.get("rx_relay_bytes", 0)

    def fold_chaos(stats: dict) -> None:
        if not stats:
            return
        c = stats.get("counters", {})
        chaos_acc["relay_forwarded"] += c.get("relay_forwarded", 0)
        chaos_acc["aborted"] += c.get("collectives_aborted", 0)
        chaos_acc["faults_armed"] += c.get("chaos_faults_armed", 0)
        chaos_acc["faults_activated"] += c.get("chaos_faults_activated", 0)
        for e in stats.get("edges", {}).values():
            chaos_acc["failovers"] += e.get("wd_reissues", 0)
            chaos_acc["relays"] += e.get("wd_relays", 0)
            chaos_acc["dup_bytes"] += e.get("dup_bytes", 0)
            chaos_acc["suspects"] += e.get("wd_suspects", 0)
            chaos_acc["confirms"] += e.get("wd_confirms", 0)

    try:
        for i in range(args.peers):
            peers.append(Peer(args.master_port, i, args.base_port + i * 16,
                              args.die_prob, seed, chaos_env.get(i),
                              args.count, chaos_args.get(i)))
            seed += 1
        deadline = time.time() + args.duration
        last_progress = time.time()
        last_total = 0
        # churn-sync schedule (docs/04): one joiner flood at 1/3 duration,
        # one busiest-seeder kill at 2/3
        flood_at = (time.time() + args.duration / 3
                    if args.sync_churn > 0 else None)
        seeder_kill_at = (time.time() + 2 * args.duration / 3
                          if args.sync_churn > 0 else None)
        while time.time() < deadline:
            time.sleep(1.0)
            # monotone: a relaunched peer restarts at 0, so dead peers'
            # counts are folded into retired_steps at relaunch time
            total = retired_steps + sum(p.steps for p in peers)
            if total > last_total:
                last_total = total
                last_progress = time.time()
            if time.time() - last_progress > args.stall_seconds:
                print(f"STALL: no progress for {args.stall_seconds}s "
                      f"(total steps {total})", flush=True)
                return 1
            # scheduled master assassination (the whole point of the
            # master-churn soak): SIGKILL, leave it dead for a window,
            # restart on the same port, peers must rejoin
            if next_master_kill is not None and time.time() >= next_master_kill:
                master_restarts += 1
                print(f"killing master (#{master_restarts}); down for "
                      f"{args.master_down_time:.1f}s", flush=True)
                t_kill = time.time()
                master.kill()
                time.sleep(args.master_down_time)
                master = MasterProc(args.master_port, args.journal,
                                    args.metrics_port)
                down = time.time() - t_kill
                master_downtime_s.append(down)
                print(f"master restarted (downtime {down:.2f}s)", flush=True)
                next_master_kill = time.time() + args.master_kill_interval
            elif not master.alive():
                # master died on its own: that's a soak failure
                print(f"MASTER DIED unexpectedly (exit code "
                      f"{master.proc.returncode})", flush=True)
                return 1
            # churn-sync events: flood half the peers at once (they come
            # back as simultaneous cold joiners), then kill the peer the
            # STATS lines prove is the busiest seeder — mid-serve death,
            # the exact failure the chunk plane exists to survive
            if flood_at is not None and time.time() >= flood_at:
                flood_at = None
                victims = peers[1:1 + max(1, args.peers // 2)]
                print(f"JOINER FLOOD: SIGKILLing {len(victims)} peers at "
                      "once", flush=True)
                sync_events["floods"] += 1
                for p in victims:
                    p.kill()
            if seeder_kill_at is not None and time.time() >= seeder_kill_at:
                seeder_kill_at = None

                def served_of(p):
                    return ((p.stats or {}).get("counters", {})
                            .get("ss_seeder_chunks_served", 0))
                busiest = max((p for p in peers if p.alive()),
                              key=served_of, default=None)
                if busiest is not None:
                    print(f"SEEDER KILL: peer {busiest.idx} "
                          f"(served={served_of(busiest)} chunks)", flush=True)
                    sync_events["seeder_kills"] += 1
                    busiest.kill()
            # relaunch the dead (the churn is the point)
            for i, p in enumerate(peers):
                if not p.alive():
                    total_relaunches += 1
                    retired_steps += p.steps
                    retired_resumes += p.resumes
                    retired_rejoins += p.rejoins
                    fold_chaos(p.stats)
                    fold_sync(p.stats)
                    sync_events["wrong"] += p.wrong_sync
                    print(f"peer {p.idx} died (steps={p.steps}); relaunching "
                          f"(#{total_relaunches})", flush=True)
                    peers[i] = Peer(args.master_port, p.idx, p.base_port,
                                    args.die_prob, seed,
                                    chaos_env.get(p.idx), args.count,
                                    chaos_args.get(p.idx))
                    seed += 1
        total = retired_steps + sum(p.steps for p in peers)
        if total == 0:
            print("SOAK FAILED: zero heartbeat steps over the whole run",
                  flush=True)
            return 1
        if next_master_kill is not None and master_restarts == 0:
            print("SOAK FAILED: master churn requested but never exercised",
                  flush=True)
            return 1
        resumes = retired_resumes + sum(p.resumes for p in peers)
        rejoins = retired_rejoins + sum(p.rejoins for p in peers)
        if master_downtime_s:
            print(f"master downtime: "
                  f"{sum(master_downtime_s) / len(master_downtime_s):.2f}s "
                  f"mean / {max(master_downtime_s):.2f}s max over "
                  f"{len(master_downtime_s)} restarts", flush=True)
        print(f"recovery mix: {resumes} session resumes, {rejoins} full "
              f"rejoins (journal={'on' if args.journal else 'off'})",
              flush=True)
        if args.metrics_port is not None:
            # fleet-health exit summary: one line an operator (or the CI
            # lane's grep) can eyeball — what the MASTER thinks the world
            # looked like when the soak ended
            try:
                import json
                import urllib.request
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{args.metrics_port}/health",
                        timeout=5) as r:
                    h = json.load(r)
                up = sum(1 for p in h["peers"] if p["up"])
                stragglers = sum(1 for e in h["edges"] if e["straggler"])
                print(f"FLEET HEALTH: epoch={h['epoch']} "
                      f"world={h['world_size']} peers_up={up}/"
                      f"{len(h['peers'])} digests={h['telemetry_digests']} "
                      f"stragglers={stragglers}", flush=True)
            except (OSError, ValueError, KeyError) as e:
                # the summary is informational: a malformed /health body
                # must not fail a soak that already passed
                print(f"FLEET HEALTH: scrape failed "
                      f"({type(e).__name__}: {e})", flush=True)
        if args.chaos:
            for p in peers:
                fold_chaos(p.stats)
            reopts = "n/a"
            if args.metrics_port is not None:
                try:
                    import urllib.request
                    with urllib.request.urlopen(
                            f"http://127.0.0.1:{args.metrics_port}/metrics",
                            timeout=5) as r:
                        for line in r.read().decode().splitlines():
                            if line.startswith(
                                    "pcclt_master_stragglers_flagged_total "):
                                reopts = line.split()[-1]
                except OSError:
                    pass
            print(f"CHAOS SUMMARY: faults_armed={chaos_acc['faults_armed']} "
                  f"activated={chaos_acc['faults_activated']} "
                  f"failovers={chaos_acc['failovers']} "
                  f"relays={chaos_acc['relays']} "
                  f"relay_forwarded={chaos_acc['relay_forwarded']} "
                  f"suspects={chaos_acc['suspects']} "
                  f"confirms={chaos_acc['confirms']} "
                  f"dup_bytes={chaos_acc['dup_bytes']} "
                  f"reopts={reopts} aborted={chaos_acc['aborted']}",
                  flush=True)
            if args.die_prob == 0 and chaos_acc["aborted"] > 0:
                # scripted faults alone must never abort an op: the ladder
                # (watchdog -> failover/relay -> re-opt) limps home instead
                print("CHAOS FAILED: scripted faults aborted collectives",
                      flush=True)
                return 1
        if args.sync_churn > 0:
            live_failed = 0
            for p in peers:
                fold_sync(p.stats)
                sync_events["wrong"] += p.wrong_sync
                live_failed += ((p.stats or {}).get("counters", {})
                                .get("syncs_failed", 0))
            print(f"SYNC SUMMARY: "
                  f"chunks_fetched={sync_acc['chunks_fetched']} "
                  f"resourced={sync_acc['chunks_resourced']} "
                  f"dup={sync_acc['chunks_dup']} "
                  f"promotions={sync_acc['promotions']} "
                  f"seeder_deaths_survived={sync_acc['seeder_deaths_survived']} "
                  f"legacy_syncs={sync_acc['legacy_syncs']} "
                  f"syncs_ok={sync_acc['syncs_ok']} "
                  f"syncs_failed={sync_acc['syncs_failed']} "
                  f"stripe_windows={sync_acc['stripe_windows']} "
                  f"stripe_bytes={sync_acc['stripe_bytes']} "
                  f"suspects={sync_acc['suspects']} "
                  f"relays={sync_acc['relays']} "
                  f"relay_bytes={sync_acc['relay_bytes']} "
                  f"floods={sync_events['floods']} "
                  f"seeder_kills={sync_events['seeder_kills']} "
                  f"wrong={sync_events['wrong']} "
                  f"aborted={live_failed} "
                  f"collective_aborts={sync_acc['aborted']}", flush=True)
            if sync_events["wrong"] > 0:
                print("SYNC FAILED: bit-wrong shared-state adoption",
                      flush=True)
                return 1
            if live_failed > 0:
                # the churn-proof claim: scheduled seeder death + joiner
                # floods never FAIL a round for a surviving peer — the
                # chunk plane re-sources around every loss
                print("SYNC FAILED: unrecovered sync failures on live peers",
                      flush=True)
                return 1
            if sync_events["floods"] == 0 or sync_events["seeder_kills"] == 0:
                print("SYNC FAILED: churn schedule never fired", flush=True)
                return 1
            if sync_acc["syncs_failed"] > 0:
                # folded across every peer life: a sync round must never
                # FAIL under scheduled churn — the chunk plane re-sources
                # around deaths and degrades (collective_aborts is NOT
                # gated: SIGKILLing a peer mid-allreduce legitimately
                # aborts the in-flight op, which survivors then retry)
                print("SYNC FAILED: sync rounds failed under churn",
                      flush=True)
                return 1
            if args.sync_degrade_mbit > 0 and sync_acc["suspects"] == 0:
                # the degrade exists to prove the serve-side watchdog sees
                # sync traffic; a soak where it never tripped proves nothing
                print("SYNC FAILED: scheduled seeder-edge degrade never "
                      "tripped the watchdog", flush=True)
                return 1
            import os as _os
            if int(_os.environ.get("PCCLT_STRIPE_CONNS", "1")) > 1 \
                    and sync_acc["stripe_bytes"] == 0:
                print("SYNC FAILED: stripe conns requested but no sync "
                      "bytes were striped", flush=True)
                return 1
        if args.fleet_scale > 0:
            fleet_stop.set()
            if fleet_thread is not None:
                fleet_thread.join(timeout=30)
            drops = lint_errs = "n/a"
            try:
                import urllib.request
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{args.metrics_port}/metrics",
                        timeout=10) as r:
                    text = r.read().decode()
                for line in text.splitlines():
                    if line.startswith(
                            "pcclt_master_digest_queue_dropped_total "):
                        drops = line.split()[-1]
                from pccl_tpu.comm import promlint
                lint_errs = str(len(promlint.lint(text)))
            except OSError:
                pass
            print(f"FLEET SCALE: observers={args.fleet_scale} "
                  f"digests_pushed={fleet_sent[0]} "
                  f"failed_rounds={fleet_failed_rounds[0]} "
                  f"queue_drops={drops} promlint_violations={lint_errs}",
                  flush=True)
            if fleet_sent[0] == 0:
                print("FLEET SCALE FAILED: digest bot never landed a round",
                      flush=True)
                return 1
            if lint_errs not in ("n/a", "0"):
                print("FLEET SCALE FAILED: /metrics is not valid "
                      "prometheus text", flush=True)
                return 1
        print(f"SOAK PASSED: {total} heartbeat steps, "
              f"{total_relaunches} relaunches, "
              f"{master_restarts} master restarts in {args.duration:.0f}s",
              flush=True)
        return 0
    finally:
        fleet_stop.set()
        for p in peers:
            p.kill()
        master.kill()


if __name__ == "__main__":
    sys.exit(main())
