"""Fault-tolerance soak test: churn real peer processes until the clock runs out.

Reference parity: /root/reference/python/tests/stress_tests/basic_stress_test/
stresstest_orchestrator.py — launch a master + N peers on loopback, let peers
randomly kill themselves mid-run (tests/ft_peer.py --die-prob), relaunch
them, and watch stdout heartbeats with a stall detector. Progress anywhere
in the group within the stall window = healthy; no progress = the collective
runtime wedged and the soak FAILS.

Usage:
    python examples/stress/stress_orchestrator.py --duration 120 --peers 3
"""

from __future__ import annotations

import argparse
import subprocess
import sys
import threading
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent.parent
PEER = REPO / "tests" / "ft_peer.py"
sys.path.insert(0, str(REPO))


class Peer:
    def __init__(self, master_port: int, idx: int, base_port: int,
                 die_prob: float, seed: int):
        self.idx = idx
        self.base_port = base_port
        cmd = [sys.executable, str(PEER), "--master-port", str(master_port),
               "--rank", str(idx), "--base-port", str(base_port),
               "--steps", "1000000", "--min-world", "2",
               "--step-interval", "0.05",
               "--die-prob", str(die_prob), "--seed", str(seed)]
        self.proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                                     stderr=subprocess.STDOUT, text=True)
        self.steps = 0
        self._t = threading.Thread(target=self._pump, daemon=True)
        self._t.start()

    def _pump(self) -> None:
        assert self.proc.stdout is not None
        for line in self.proc.stdout:
            if line.startswith("STEP "):
                self.steps += 1

    def alive(self) -> bool:
        return self.proc.poll() is None

    def kill(self) -> None:
        if self.alive():
            self.proc.kill()
        self.proc.wait(timeout=10)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--duration", type=float, default=120.0)
    ap.add_argument("--peers", type=int, default=3)
    ap.add_argument("--die-prob", type=float, default=0.002)
    ap.add_argument("--master-port", type=int, default=48900)
    ap.add_argument("--base-port", type=int, default=58000)
    ap.add_argument("--stall-seconds", type=float, default=120.0,
                    help="fail if NO peer makes progress for this long "
                         "(reference uses 5 minutes)")
    args = ap.parse_args()

    from pccl_tpu.comm import MasterNode

    master = MasterNode("0.0.0.0", args.master_port)
    master.run()
    peers: list[Peer] = []
    seed = 1
    total_relaunches = 0
    retired_steps = 0  # steps of peers that died; keeps the total monotone
    try:
        for i in range(args.peers):
            peers.append(Peer(master.port, i, args.base_port + i * 16,
                              args.die_prob, seed))
            seed += 1
        deadline = time.time() + args.duration
        last_progress = time.time()
        last_total = 0
        while time.time() < deadline:
            time.sleep(1.0)
            # monotone: a relaunched peer restarts at 0, so dead peers'
            # counts are folded into retired_steps at relaunch time
            total = retired_steps + sum(p.steps for p in peers)
            if total > last_total:
                last_total = total
                last_progress = time.time()
            if time.time() - last_progress > args.stall_seconds:
                print(f"STALL: no progress for {args.stall_seconds}s "
                      f"(total steps {total})", flush=True)
                return 1
            # relaunch the dead (the churn is the point)
            for i, p in enumerate(peers):
                if not p.alive():
                    total_relaunches += 1
                    retired_steps += p.steps
                    print(f"peer {p.idx} died (steps={p.steps}); relaunching "
                          f"(#{total_relaunches})", flush=True)
                    peers[i] = Peer(master.port, p.idx, p.base_port,
                                    args.die_prob, seed)
                    seed += 1
        total = retired_steps + sum(p.steps for p in peers)
        if total == 0:
            print("SOAK FAILED: zero heartbeat steps over the whole run",
                  flush=True)
            return 1
        print(f"SOAK PASSED: {total} heartbeat steps, "
              f"{total_relaunches} relaunches in {args.duration:.0f}s",
              flush=True)
        return 0
    finally:
        for p in peers:
            p.kill()
        master.interrupt()
        master.destroy()


if __name__ == "__main__":
    sys.exit(main())
