"""nanoGPT DDP over the WAN ring — per-step gradient averaging.

Reference parity: /root/reference/python/examples/nanogptddp/train_pccl.py
(torch DDP loop with pcclAllReduce per step). TPU-first redesign:

- each peer process is one SLICE: the train step is a jitted SPMD program
  over the local device mesh (dp x tp — pass --tp for in-slice tensor
  parallelism; this is the reference's FSDP x PCCL grid pattern,
  docs/md/8_CommonFootguns.md, with XLA sharding in place of FSDP);
- per-step gradients cross the ring as ONE flat fp32 vector
  (HierarchicalAllReduce: ICI in-jit, TCP across slices) with optional
  on-the-wire quantization (--quantize minmax);
- peer churn: ConnectionLost/Aborted -> update_topology -> retry, and
  pending joiners are admitted between steps.

Run (2 peers on loopback):
    python -m pccl_tpu.comm.master --port 48500 &
    python examples/nanogpt_ddp/train_ddp.py --master-port 48500 \
        --base-port 56000 --min-world 2 --steps 50 &
    python examples/nanogpt_ddp/train_ddp.py --master-port 48500 \
        --base-port 56100 --min-world 2 --steps 50
"""

from __future__ import annotations

import argparse
import functools
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
sys.path.insert(0, str(Path(__file__).resolve().parent.parent.parent))

import numpy as np

import common


def main() -> int:
    ap = argparse.ArgumentParser()
    common.add_comm_args(ap)
    ap.add_argument("--steps", type=int, default=50)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--block", type=int, default=64)
    ap.add_argument("--lr", type=float, default=1e-3)
    ap.add_argument("--tp", type=int, default=0,
                    help="in-slice tensor-parallel degree (0 = auto mesh)")
    ap.add_argument("--grad-accum", type=int, default=1,
                    help="gradient accumulation microbatches per step "
                         "(reference gradient_accumulation_steps); the "
                         "ring still moves ONE averaged gradient per step")
    common.add_lr_schedule_args(ap)
    ap.add_argument("--eval-every", type=int, default=0,
                    help="every N steps, report mean loss over "
                         "--eval-batches held-out batches (reference "
                         "estimate_loss)")
    ap.add_argument("--eval-batches", type=int, default=4)
    ap.add_argument("--checkpoint-dir", default=None,
                    help="save {params, opt_state} here every "
                         "--checkpoint-every steps and resume from the "
                         "newest snapshot (reference ckpt.pt save/resume)")
    ap.add_argument("--checkpoint-every", default=20,
                    type=lambda v: max(1, int(v)))
    ap.add_argument("--quantize", choices=["none", "minmax"], default="none")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--shm-staging", action="store_true",
                    help="stage the flat gradient in a registered shm buffer "
                         "(zero-copy ring when peers share this host)")
    common.add_data_args(ap)
    common.add_model_args(ap)
    args = ap.parse_args()

    common.force_cpu_if_requested()
    import jax
    import jax.numpy as jnp
    import optax

    from pccl_tpu.comm import DataType
    from pccl_tpu.parallel import mesh as mesh_lib
    from pccl_tpu.parallel.hierarchical import HierarchicalAllReduce
    from pccl_tpu.parallel.train import family

    comm = common.connect(args)

    # --- in-slice SPMD setup ---
    devices = jax.devices()
    if args.tp > 0:
        shape = (max(1, len(devices) // args.tp), args.tp)
        mesh = mesh_lib.make_mesh(devices[: shape[0] * shape[1]], ("dp", "tp"),
                                  shape)
    else:
        mesh = mesh_lib.make_mesh(devices, ("dp", "tp"))
    cfg = common.model_config(args, char_level=args.data == "text")
    model, sharding_fn = family(cfg)  # gpt or llama by config family
    param_sharding = sharding_fn(mesh, cfg)  # must match make_train_state's
    data_sharding = mesh_lib.batch_sharding(mesh)

    from pccl_tpu.parallel.train import make_train_state

    schedule = common.make_schedule(args, args.lr, args.steps)
    params, tx, opt_state = make_train_state(
        jax.random.PRNGKey(args.seed), cfg, mesh, lr=args.lr,
        schedule=schedule)

    base_lg = jax.value_and_grad(functools.partial(model.loss_fn, cfg=cfg))
    if args.grad_accum > 1:
        # tokens/targets arrive [A, B, T]; the shared library wrapper
        # (parallel/train.py:accum_value_and_grad) scans the microbatches
        # so one microbatch's activations are live at a time
        from jax.sharding import NamedSharding, PartitionSpec as P

        from pccl_tpu.parallel.train import accum_value_and_grad

        data_sharding = NamedSharding(mesh, P(None, *data_sharding.spec))
        base_lg = accum_value_and_grad(base_lg, args.grad_accum)
    loss_and_grad = jax.jit(
        base_lg,
        in_shardings=(param_sharding, data_sharding, data_sharding),
    )

    @functools.partial(jax.jit, donate_argnums=(0, 1),
                       out_shardings=(param_sharding, None))
    def apply(params, opt_state, grads):
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state

    # --- cross-slice gradient averaging ---
    # params serve as the gradient template: same shapes/dtypes/shardings.
    # Factory, not a one-off: a KickedError recovery below reconnects and
    # needs a ring bound to the fresh communicator.
    def make_ring():
        return HierarchicalAllReduce(
            comm, params, quantization=common.quant_from_arg(args.quantize),
            quantized_dtype=DataType.UINT8, shm_staging=args.shm_staging)

    ring = make_ring()

    from pccl_tpu.utils.profiler import Profiler

    prof = Profiler(enabled=args.profile or bool(args.trace_out))
    next_batch = common.make_batch_fn(args, cfg.vocab_size)  # per-peer shard
    # background device prefetch: the H2D copy of batch k+1 overlaps the
    # device compute of batch k (pccl_tpu.utils.data)
    from pccl_tpu.utils.data import prefetch_to_device

    def _replicate_loose(tree):
        """Optimizer scalars (step counts) come back from checkpoint
        restore or shared-state adoption COMMITTED to a single device
        while params are mesh-sharded — one jit cannot mix the two device
        sets, so re-place any non-mesh-sharded leaf replicated."""
        from jax.sharding import NamedSharding

        return jax.tree.map(
            lambda x: x if isinstance(getattr(x, "sharding", None),
                                      NamedSharding)
            else jax.device_put(x, mesh_lib.replicated(mesh)), tree)

    ckpt = None
    start = 0
    if args.checkpoint_dir:
        from pccl_tpu.utils.checkpoint import Checkpointer

        ckpt = Checkpointer(args.checkpoint_dir)
        latest = ckpt.latest_step()
        if latest is not None:
            tree = ckpt.restore({"params": params, "opt_state": opt_state},
                                latest)
            params, opt_state = tree["params"], tree["opt_state"]
            opt_state = _replicate_loose(opt_state)
            start = latest
            # advance the deterministic data stream past the replayed
            # prefix — otherwise resumed steps retrain on the exact
            # batches steps [0, start) already consumed. MUST happen
            # before the prefetch thread below starts drawing.
            for _ in range(start * max(1, args.grad_accum)):
                next_batch()
            print(f"resumed from step {latest}", flush=True)

    def batches():
        while True:
            if args.grad_accum > 1:
                ms = [next_batch() for _ in range(args.grad_accum)]
                yield (np.stack([m[0] for m in ms]),
                       np.stack([m[1] for m in ms]))
            else:
                yield next_batch()

    feed = prefetch_to_device(batches(), size=2, sharding=data_sharding)

    # held-out eval (reference estimate_loss): the val split — a disjoint
    # tail slice of the text corpus (or a fresh synthetic stream, which is
    # held out by construction) — through a grad-free jitted loss
    eval_fn = eval_batch = None
    if args.eval_every > 0:
        eval_fn = jax.jit(functools.partial(model.loss_fn, cfg=cfg))
        eval_batch = common.make_batch_fn(args, cfg.vocab_size, split="val")

    # --- per-step shared-state sync (reference train_pccl.py keeps its
    # model+optimizer in the pccl shared state and syncs every step) ---
    # The DDP invariant is IDENTICAL params on every peer; topology alone
    # cannot keep it — a late joiner starts from seed params and a
    # checkpoint-resumed peer from its snapshot. The sync REVISION is the
    # master's strict one-increment counter, NOT the step: after the first
    # sync every peer offers info.revision + 1, and the step consensus
    # rides in the "ddp.step" entry. Revision equals step only on the
    # common path (a cohort that started together), and the first offer
    # depends on how this peer came up:
    #  * fresh start — offer revision 0 (a late joiner's 0 can never trip
    #    the master's `revision > last+1` kick; if the cohort is ahead the
    #    mismatch marks us outdated and we adopt params/opt/step below);
    #  * checkpoint resume into a possibly-initialized cohort — offering
    #    the snapshot step would be revision last+2-or-more and the master
    #    KICKS for it ("shared-state revision increment violation"; before
    #    this fix the retry loop below then spun forever on the dead
    #    conn). The first sync is instead a probe at revision 0 — in
    #    receive-only SPIRIT, but declared ENFORCE_POPULAR because the
    #    master's all-or-nothing mixing rule (reference parity) kicks a
    #    literal rx-only request alongside enforce-popular incumbents. A
    #    revision-0 enforce-popular offer is never kickable (0 <= last+1
    #    always) and never wins an election against revision-matched
    #    incumbents, so against an initialized cohort it degenerates to
    #    "adopt their params/opt/step"; in a whole-cohort restart (every
    #    member probing at 0) the popularity election converges everyone
    #    onto one checkpoint's content instead of kicking the round;
    #  * checkpoint resume running solo (world 1) — offer the snapshot
    #    step; the fresh master bootstraps at any first revision.
    # Cost note: without PCCLT_SS_HASH=simple-tpu the hash compare stages
    # every leaf to the host each step — fine for example scale; TPU
    # deployments set the env var group-wide so clean syncs ship 8 bytes
    # per entry instead (pccl_tpu.ops.hashing, TensorInfo.from_jax_device).
    import os as _os

    from pccl_tpu.comm import (KickedError, PcclError, SharedState,
                               SharedStateSyncStrategy, TensorInfo)

    _mk = (TensorInfo.from_jax_device
           if _os.environ.get("PCCLT_SS_HASH") == "simple-tpu"
           else TensorInfo.from_jax)

    sync_ctl = {"next_revision": None,  # None until the first sync lands
                "probe": start > 0}     # resumed: rx-only@0 first (see above)

    def sync_state(params, opt_state, step):
        leaves_p, tdef_p = jax.tree.flatten(params)
        leaves_o, tdef_o = jax.tree.flatten(opt_state)
        step_arr = np.array([step], dtype=np.uint64)
        entries = ([_mk(f"ddp.p{i}", l) for i, l in enumerate(leaves_p)]
                   + [_mk(f"ddp.o{i}", l) for i, l in enumerate(leaves_o)]
                   + [TensorInfo.from_numpy("ddp.step", step_arr)])
        probe = sync_ctl["probe"] and comm.world_size >= 2
        if probe:
            revision = 0  # adopt-the-cohort probe (see the comment above)
        else:
            revision = (sync_ctl["next_revision"]
                        if sync_ctl["next_revision"] is not None else step)
        strategy = SharedStateSyncStrategy.ENFORCE_POPULAR
        st = SharedState(entries, revision=revision)
        # churn mid-election: retry at the SAME revision until the survivor
        # group elects (grid_diloco.py's sync_with_retry contract). Training
        # through a failed sync would increment the offer and violate the
        # master's one-increment rule. A kick is terminal for this
        # communicator — surface it instead of spinning on a dead conn.
        while True:
            try:
                info = comm.sync_shared_state(st, strategy)
                break
            except KickedError:
                raise
            except PcclError:
                time.sleep(0.1)
                try:
                    if comm.are_peers_pending():
                        comm.update_topology()
                except KickedError:
                    raise
                except PcclError:
                    pass
        sync_ctl["next_revision"] = info.revision + 1
        sync_ctl["probe"] = False
        if info.rx_bytes:  # outdated: adopt the cohort's state
            n = len(leaves_p)
            params = jax.tree.unflatten(
                tdef_p, [e.jax_value() for e in entries[:n]])
            opt_state = _replicate_loose(jax.tree.unflatten(
                tdef_o, [e.jax_value() for e in entries[n:n + len(leaves_o)]]))
            step = int(step_arr[0])
            print(f"adopted shared state at step {step}", flush=True)
        return params, opt_state, step

    first_loss = last_loss = None
    step = start
    while step < args.steps:
        common.admit_pending(comm)
        if comm is not None:
            try:
                params, opt_state, step = sync_state(params, opt_state, step)
            except KickedError:
                # Safety net: a kick is terminal for the communicator (the
                # old code spun forever retrying on the dead conn). The
                # probe path above cannot be kicked, but a solo-resumed
                # peer whose cohort materialized mid-run, or a master-side
                # policy we did not anticipate, still can. Reconnect and
                # re-offer revision 0 enforce-popular — never kickable, so
                # this cannot loop; the election then converges us onto
                # the cohort's content (incl. its ddp.step).
                print("kicked during sync; reconnecting with revision-0 "
                      "enforce-popular offer", flush=True)
                try:
                    comm.destroy()
                except PcclError:
                    pass
                comm = common.connect(args)
                ring = make_ring()
                sync_ctl["probe"] = False
                sync_ctl["next_revision"] = 0
                continue
            if step >= args.steps:
                break
        tok, tgt = next(feed)
        with prof.section("fwd+bwd"):
            loss, grads = loss_and_grad(params, tok, tgt)
        with prof.section("ring/all_reduce"):
            grads = ring.all_reduce(grads)  # global mean (identity when solo)
        with prof.section("apply"):
            params, opt_state = apply(params, opt_state, grads)
        loss = float(loss)
        first_loss = first_loss if first_loss is not None else loss
        last_loss = loss
        world = comm.world_size if comm is not None else 1
        print(f"step {step} loss {loss:.4f} world {world}", flush=True)
        if eval_fn is not None and (step + 1) % args.eval_every == 0:
            vals = []
            for _ in range(args.eval_batches):
                et, ey = eval_batch()
                vals.append(float(eval_fn(params, jnp.asarray(et),
                                          jnp.asarray(ey))))
            print(f"eval step {step} loss {np.mean(vals):.4f}", flush=True)
        if ckpt is not None and (step + 1) % args.checkpoint_every == 0:
            ckpt.save(step + 1, {"params": params, "opt_state": opt_state})
        step += 1

    common.finish_profile(args, prof)
    return common.report_final(first_loss, last_loss, comm)


if __name__ == "__main__":
    sys.exit(main())
