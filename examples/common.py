"""Shared plumbing for the example training loops.

Reference parity: the reference examples (/root/reference/python/examples/
nanogptddp/train_pccl.py, nanogpt_diloco/sync_diloco.py) share the same
skeleton — connect to the master, wait for the world, per-step topology
updates, retry on churn. Here that skeleton is TPU-first: every peer process
is one "slice" running a jitted SPMD step over its local device mesh, and
only the cross-slice hop rides the TCP ring.

The dataset is synthetic (zero-egress environment): token t+1 is an affine
function of token t plus rare noise, so next-token loss falls fast and
convergence is assertable in CI.
"""

from __future__ import annotations

import argparse
import os
import time

import numpy as np


def add_comm_args(ap: argparse.ArgumentParser) -> None:
    ap.add_argument("--master-ip", default="127.0.0.1")
    ap.add_argument("--master-port", type=int, default=48500)
    ap.add_argument("--base-port", type=int, default=56000,
                    help="p2p/shared-state/bench listen ports (bump-allocated)")
    ap.add_argument("--min-world", type=int, default=1,
                    help="wait until this many peers joined before training")
    ap.add_argument("--peer-group", type=int, default=0)
    ap.add_argument("--connect-timeout", type=float, default=120.0,
                    help="seconds to wait for --min-world peers (raise when "
                         "many peers cold-start jax on a loaded host)")
    ap.add_argument("--solo", action="store_true",
                    help="run without a comm (single slice, no master)")


def connect(args):
    """Create + connect a Communicator and wait for --min-world peers.
    Returns None under --solo."""
    if args.solo:
        return None
    from pccl_tpu.comm import Communicator

    comm = Communicator(args.master_ip, args.master_port,
                        peer_group=args.peer_group,
                        p2p_port=args.base_port, ss_port=args.base_port + 4,
                        bench_port=args.base_port + 8)
    comm.connect()
    deadline = time.time() + getattr(args, "connect_timeout", 120.0)
    while comm.world_size < args.min_world:
        if time.time() > deadline:
            raise TimeoutError(f"world never reached {args.min_world}")
        if comm.are_peers_pending():
            comm.update_topology()
        time.sleep(0.02)
    return comm


def admit_pending(comm) -> None:
    """Between-steps topology vote (reference update-topology loop)."""
    if comm is not None and comm.are_peers_pending():
        comm.update_topology()


def synth_batch(rng: np.random.RandomState, batch: int, block: int,
                vocab: int):
    """Learnable synthetic LM data: x[t+1] = (5*x[t] + 7) % vocab, with 5%
    uniform noise. Returns (tokens, targets) int32 [B, T]."""
    x = np.empty((batch, block + 1), dtype=np.int64)
    x[:, 0] = rng.randint(0, vocab, size=batch)
    for t in range(block):
        x[:, t + 1] = (5 * x[:, t] + 7) % vocab
    noise = rng.rand(batch, block + 1) < 0.05
    x[noise] = rng.randint(0, vocab, size=int(noise.sum()))
    return x[:, :-1].astype(np.int32), x[:, 1:].astype(np.int32)


_CORPUS = None


def text_corpus(max_bytes: int = 2 << 20) -> np.ndarray:
    """Real char-level corpus without network egress: concatenated Python
    standard-library sources (docstring-heavy English + code). This plays
    the role of the reference's real-dataset e2e runs (mnist_ddp /
    mnist_diloco, /root/reference/python/tests/end_to_end/) — genuine,
    structured data rather than a synthetic token rule. Byte-level,
    vocab 256, deterministic file order."""
    global _CORPUS
    if _CORPUS is not None:
        return _CORPUS
    import sysconfig
    from pathlib import Path

    stdlib = Path(sysconfig.get_paths()["stdlib"])
    buf = bytearray()
    for f in sorted(stdlib.glob("*.py")):
        try:
            buf += f.read_bytes()
        except OSError:
            continue
        if len(buf) >= max_bytes:
            break
    assert len(buf) > 64 * 1024, "stdlib corpus unexpectedly small"
    _CORPUS = np.frombuffer(bytes(buf[:max_bytes]), dtype=np.uint8)
    return _CORPUS


def text_batch(corpus: np.ndarray, rng: np.random.RandomState, batch: int,
               block: int):
    """Random contiguous char windows -> (tokens, targets) int32 [B, T].
    (Direct sampler; make_batch_fn routes the text path through the
    library's TokenDataset instead.)"""
    idx = rng.randint(0, len(corpus) - block - 1, size=batch)
    x = np.stack([corpus[i:i + block + 1] for i in idx])
    return x[:, :-1].astype(np.int32), x[:, 1:].astype(np.int32)


def add_data_args(ap: argparse.ArgumentParser) -> None:
    ap.add_argument("--data", choices=["synthetic", "text"],
                    default="synthetic",
                    help="synthetic affine tokens, or real char-level text "
                         "(python stdlib sources)")


def add_model_args(ap: argparse.ArgumentParser) -> None:
    ap.add_argument("--model", default="nano",
                    help="model preset: nano (CI default) | tiny | gpt2 | "
                         "gpt2-medium | gpt2-large | gpt2-xl "
                         "(pccl_tpu.models.gpt.PRESETS); with "
                         "--family llama: nano | tiny | 700m | 1b | 7b | 8b")
    ap.add_argument("--family", choices=["gpt", "llama"], default="gpt",
                    help="model family (pccl_tpu.models)")
    ap.add_argument("--profile", action="store_true",
                    help="print a per-section time table at the end "
                         "(pccl_tpu.utils.profiler)")
    ap.add_argument("--trace-out", default=None,
                    help="write a Chrome trace-event JSON of the run")


def model_config(args, *, char_level: bool):
    """Model config from --family and the --model preset, with --block as
    the sequence length; char-level text data caps the vocab at 256 bytes."""
    from pccl_tpu.models import gpt, llama

    family = gpt if getattr(args, "family", "gpt") == "gpt" else llama
    overrides = {"block_size": args.block}
    if char_level:
        overrides["vocab_size"] = 256
    return family.named_config(args.model, **overrides)


def finish_profile(args, prof) -> None:
    if prof is None:
        return
    if args.trace_out:
        prof.export_chrome_trace(args.trace_out)
        print(f"trace written to {args.trace_out}", flush=True)
    if args.profile:
        print(prof.summary(), flush=True)


def make_batch_fn(args, vocab: int, split: str = "train"):
    """Per-peer batch sampler for the chosen dataset; the shard is keyed
    off the peer's base port either way. The text path samples through the
    library's TokenDataset (random-crop next-token pairs, disjoint stream
    per worker_index); split="val" crops a DISJOINT tail 10% of the corpus
    (the reference's train.bin/val.bin estimate_loss split) — a different
    rng stream alone would still sample the training text. The synthetic
    rule is the distribution itself, so there a fresh stream IS held out."""
    if getattr(args, "data", "synthetic") == "text":
        from pccl_tpu.utils.data import TokenDataset

        corpus = text_corpus()
        cut = int(len(corpus) * 0.9)
        corpus = corpus[cut:] if split == "val" else corpus[:cut]
        ds = TokenDataset(corpus, args.block, args.batch,
                          seed=1000 if split == "train" else 7919,
                          worker_index=args.base_port % 997)
        return ds.sample
    rng = data_rng(args) if split == "train" else \
        np.random.RandomState(7919 + (args.base_port % 997))
    return lambda: synth_batch(rng, args.batch, args.block, vocab)


def quant_from_arg(name: str):
    """Map the --quantize CLI choice to a QuantizationAlgorithm."""
    from pccl_tpu.comm import QuantizationAlgorithm

    return {"none": QuantizationAlgorithm.NONE,
            "minmax": QuantizationAlgorithm.MIN_MAX,
            "zps": QuantizationAlgorithm.ZERO_POINT_SCALE}[name]


def data_rng(args) -> np.random.RandomState:
    """Per-peer data shard: seeded off the peer's unique base port."""
    return np.random.RandomState(1000 + (args.base_port % 997))


def report_final(first_loss, last_loss, comm) -> int:
    """Print the FINAL line (parsed by tests/test_examples_e2e.py) and
    return the process exit code (0 = loss decreased). None losses mean no
    step ran (e.g. a checkpoint resume at/past --outer-steps) — report
    cleanly and exit 0."""
    # FINAL goes out BEFORE destroy: a churn-wedged teardown must not
    # suppress the result line the e2e harness parses
    if first_loss is None or last_loss is None:
        print("FINAL no steps ran (resumed at or past the step budget)",
              flush=True)
        code = 0
    else:
        print(f"FINAL first_loss={first_loss:.4f} last_loss={last_loss:.4f}",
              flush=True)
        code = 0 if last_loss < first_loss else 4
    if comm is not None:
        comm.destroy()
    return code


def force_cpu_if_requested() -> None:
    """Honor JAX_PLATFORMS even when a TPU plugin tries to override it
    (must run before first jax backend use)."""
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        import jax

        try:
            jax.config.update("jax_platforms", plat)
        except Exception:  # noqa: BLE001 — backend already initialized
            pass


def add_lr_schedule_args(ap: argparse.ArgumentParser) -> None:
    ap.add_argument("--lr-schedule", choices=["const", "cosine"],
                    default="const",
                    help="cosine = linear warmup then cosine decay to "
                         "--min-lr over the run (reference get_lr)")
    ap.add_argument("--warmup-steps", type=int, default=0)
    ap.add_argument("--min-lr", type=float, default=0.0)


def make_schedule(args, peak_lr: float, total_steps: int, offset: int = 0):
    """The --lr-schedule CLI -> an optax schedule (or None for const).
    offset shifts the schedule's step count — a resumed run continues the
    decay from where it left off instead of rerunning warmup (the inner
    optimizer state, including its step count, is rebuilt fresh on
    resume)."""
    if getattr(args, "lr_schedule", "const") != "cosine":
        return None
    from pccl_tpu.parallel.train import cosine_warmup_schedule

    base = cosine_warmup_schedule(peak_lr, total_steps, args.warmup_steps,
                                  args.min_lr)
    if not offset:
        return base
    return lambda count: base(count + offset)
