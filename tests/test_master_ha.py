"""Master high availability: journaled master state + client session resume.

The tentpole contract (docs/10_high_availability.md): with a journal and
resume enabled, SIGKILLing the master mid-training and restarting it on the
same port is a BLIP — every peer re-attaches under its old UUID (zero
re-registrations, asserted via the epoch/resume attributes), the
shared-state revision stays monotonic across the outage, and no shared-state
bytes are retransmitted on resume (asserted via the sync byte counters and
the per-edge connect counters: the p2p mesh is never rebuilt). Without a
journal, the failure path stays clean: reconnect budget exhausted ->
MasterUnreachableError within the configured deadline, no hang.

Multi-peer behavior is tested with real processes, never mocks (the repo's
stress-test discipline; see tests/test_fault_tolerance.py).
"""

from __future__ import annotations

import signal
import socket
import subprocess
import sys
import threading
import time
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
PEER = REPO / "tests" / "ha_peer.py"
LIB = REPO / "pccl_tpu" / "native" / "build" / "libpcclt.so"
pytestmark = pytest.mark.skipif(not LIB.exists(), reason="native lib not built")

from conftest import alloc_ports as _next_port  # noqa: E402


class MasterProc:
    """python -m pccl_tpu.comm.master as a SIGKILL-able subprocess."""

    def __init__(self, port: int, journal: str | None = None):
        self.port = port
        cmd = [sys.executable, "-m", "pccl_tpu.comm.master",
               "--port", str(port)]
        if journal:
            cmd += ["--journal", journal]
        self.proc = subprocess.Popen(cmd, cwd=str(REPO),
                                     stdout=subprocess.PIPE,
                                     stderr=subprocess.STDOUT, text=True)
        deadline = time.time() + 20
        while time.time() < deadline:
            try:
                with socket.create_connection(("127.0.0.1", port), timeout=1):
                    return
            except OSError:
                if self.proc.poll() is not None:
                    raise RuntimeError(
                        f"master died on startup: {self.proc.stdout.read()}")
                time.sleep(0.05)
        raise RuntimeError("master never started listening")

    def sigkill(self) -> None:
        if self.proc.poll() is None:
            self.proc.send_signal(signal.SIGKILL)
        self.proc.wait(timeout=10)


class HaPeer:
    def __init__(self, master_port: int, rank: int, base_port: int, **kw):
        cmd = [sys.executable, str(PEER), "--master-port", str(master_port),
               "--rank", str(rank), "--base-port", str(base_port)]
        for k, v in kw.items():
            cmd += [f"--{k.replace('_', '-')}", str(v)]
        self.proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                                     stderr=subprocess.STDOUT, text=True)
        self.lines: list[str] = []
        self._t = threading.Thread(target=self._pump, daemon=True)
        self._t.start()

    def _pump(self) -> None:
        assert self.proc.stdout is not None
        for line in self.proc.stdout:
            self.lines.append(line.rstrip())

    def steps(self) -> list[dict]:
        out = []
        for ln in self.lines:
            if not ln.startswith("STEP "):
                continue
            d = {"step": int(ln.split()[1])}
            for tok in ln.split()[2:]:
                k, v = tok.split("=")
                d[k] = int(v)
            out.append(d)
        return out

    def wait_for_step(self, step: int, timeout: float = 90) -> bool:
        deadline = time.time() + timeout
        while time.time() < deadline:
            if any(s["step"] >= step for s in self.steps()):
                return True
            if self.proc.poll() is not None:
                return any(s["step"] >= step for s in self.steps())
            time.sleep(0.05)
        return False

    def join(self, timeout: float = 120) -> int:
        try:
            return self.proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            self.proc.kill()
            raise

    def kill(self) -> None:
        if self.proc.poll() is None:
            self.proc.kill()
        self.proc.wait(timeout=10)


def test_master_sigkill_restart_is_a_blip(tmp_path):
    """SIGKILL + restart the master mid-training on a 3-peer loopback world:
    collectives resume, zero re-registrations (same uuids: resumes>=1 and
    epoch 2 on every peer), shared-state revision monotonic across the
    outage, and no shared-state retransmit nor p2p reconnect on resume."""
    journal = str(tmp_path / "master.journal")
    port = _next_port()
    base = _next_port(64)
    master = MasterProc(port, journal)
    peers = [HaPeer(port, r, base + r * 16, steps=28, min_world=3,
                    step_interval=0.15) for r in range(3)]
    try:
        for p in peers:
            assert p.wait_for_step(5), f"peer stalled: {p.lines[-8:]}"
        master.sigkill()
        time.sleep(1.0)  # a real outage window, mid-training
        master = MasterProc(port, journal)

        for i, p in enumerate(peers):
            assert p.join() == 0, f"peer {i} failed: {p.lines[-12:]}"
            steps = p.steps()
            assert steps[-1]["step"] == 27, f"peer {i} incomplete: {steps[-1]}"
            # zero re-registrations: the outage was absorbed by session
            # resume (epoch bumped to 2) under the original uuid
            assert steps[-1]["resumes"] >= 1, f"peer {i} never resumed"
            assert steps[-1]["epoch"] == 2, f"peer {i} epoch: {steps[-1]}"
            assert not any("FATAL" in ln or "REJOIN" in ln for ln in p.lines)
            # world never shrank: membership survived the restart intact
            assert all(s["world"] == 3 for s in steps), \
                f"peer {i} world dipped: {sorted({s['world'] for s in steps})}"
            # shared-state revision monotonic ACROSS the outage, and it kept
            # advancing afterwards
            revs = [s["rev"] for s in steps]
            assert revs == sorted(revs), f"peer {i} revision regressed: {revs}"
            pre = [s for s in steps if s["resumes"] == 0]
            post = [s for s in steps if s["resumes"] >= 1]
            assert post, f"peer {i} made no post-resume steps"
            assert post[-1]["rev"] > pre[-1]["rev"], \
                f"peer {i} revision stalled across the outage"
            # no full shared-state retransmit on resume: every post-resume
            # sync moved ZERO bytes (hashes agree; only control traffic)
            assert all(s["ss_rx"] == 0 and s["ss_tx"] == 0 for s in post), \
                f"peer {i} resynced bytes post-resume: {post}"
            # the p2p mesh was kept alive: no new data-plane connections
            # after the resume (per-edge connect counters are monotonic)
            assert post[-1]["conns"] == pre[-1]["conns"], \
                f"peer {i} rebuilt p2p conns: {pre[-1]} -> {post[-1]}"
    finally:
        for p in peers:
            p.kill()
        master.sigkill()


def test_no_journal_fails_fast(tmp_path):
    """Journal-disabled failure path: with no journal and the reconnect
    budget exhausted, peers surface MasterUnreachableError within the
    configured deadline — no hang, no leaked subprocess."""
    port = _next_port()
    base = _next_port(64)
    master = MasterProc(port, journal=None)
    # small, deterministic budget: 3 attempts x (<=200 ms backoff)
    peers = [HaPeer(port, r, base + r * 16, steps=1000, min_world=2,
                    step_interval=0.1, reconnect_attempts=3,
                    reconnect_backoff_ms=50, reconnect_cap_ms=200)
             for r in range(2)]
    try:
        for p in peers:
            assert p.wait_for_step(3), f"peer stalled: {p.lines[-8:]}"
        t_kill = time.time()
        master.sigkill()  # and never restart
        for i, p in enumerate(peers):
            # budget: ~0.3 s of backoff + connect failures; 30 s is a hard
            # ceiling that still catches a 300/600 s protocol-timeout hang
            rc = p.join(timeout=30)
            assert rc == 4, f"peer {i} exit {rc}: {p.lines[-12:]}"
            assert any("FATAL MasterUnreachableError" in ln for ln in p.lines), \
                f"peer {i}: {p.lines[-12:]}"
        assert time.time() - t_kill < 30
    finally:
        for p in peers:
            p.kill()
        master.sigkill()


def test_resume_rejected_without_journal(tmp_path):
    """A master restarted WITHOUT a journal cannot resume sessions: the
    resume is rejected and the client surfaces MasterUnreachableError (the
    identity-reset signal the rejoin path keys on) instead of hanging."""
    port = _next_port()
    base = _next_port(64)
    master = MasterProc(port, journal=None)
    peers = [HaPeer(port, r, base + r * 16, steps=1000, min_world=2,
                    step_interval=0.1, reconnect_attempts=10,
                    reconnect_backoff_ms=50, reconnect_cap_ms=300)
             for r in range(2)]
    try:
        for p in peers:
            assert p.wait_for_step(3), f"peer stalled: {p.lines[-8:]}"
        master.sigkill()
        time.sleep(0.5)
        master = MasterProc(port, journal=None)  # fresh state, no limbo
        for i, p in enumerate(peers):
            rc = p.join(timeout=60)
            assert rc == 4, f"peer {i} exit {rc}: {p.lines[-12:]}"
            assert any("FATAL MasterUnreachableError" in ln for ln in p.lines)
    finally:
        for p in peers:
            p.kill()
        master.sigkill()
