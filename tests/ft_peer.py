"""Fault-tolerance test peer (subprocess worker).

Reference parity: the stress-test peers of the reference
(/root/reference/python/tests/stress_tests/basic_stress_test/stresstest_peer.py)
— loop collectives, print heartbeats, optionally die mid-run; the
orchestrating test watches stdout and asserts survivors keep making progress.
"""

from __future__ import annotations

import argparse
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np  # noqa: E402


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--master-port", type=int, required=True)
    ap.add_argument("--rank", type=int, default=0,
                    help="label for heartbeat lines (ports come from --base-port)")
    ap.add_argument("--steps", type=int, default=40)
    ap.add_argument("--min-world", type=int, default=2)
    ap.add_argument("--join-delay", type=float, default=0.0)
    ap.add_argument("--die-at", type=int, default=-1,
                    help="exit(0) abruptly before this step (simulated crash)")
    ap.add_argument("--die-prob", type=float, default=0.0,
                    help="per-step probability of abrupt exit (soak testing)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--base-port", type=int, required=True)
    ap.add_argument("--count", type=int, default=4096)
    ap.add_argument("--quantize", choices=["none", "minmax"], default="none",
                    help="exercise the quantized wire path under churn")
    ap.add_argument("--peer-group", type=int, default=0,
                    help="collectives/shared-state partition (grid pattern)")
    ap.add_argument("--step-interval", type=float, default=0.0,
                    help="sleep between steps (paces incumbents so churn "
                         "events land mid-run)")
    ap.add_argument("--sync-state", type=int, default=0, metavar="ELEMS",
                    help="churn-sync lane (docs/04): sync an ELEMS-float32 "
                         "shared state every step (revision advances one "
                         "per step, content = full(revision)); a relaunched "
                         "peer offers revision 0 and adopts as a cold "
                         "joiner. Prints 'WRONG SYNC' and exits 3 if "
                         "adopted content ever disagrees with its revision.")
    ap.add_argument("--stats-every", type=int, default=0,
                    help="print a 'STATS {json}' line with the comm's "
                         "counter/edge snapshot every N steps (the stress "
                         "orchestrator's CHAOS SUMMARY aggregates these)")
    ap.add_argument("--inject-spec", default="",
                    help="chaos schedule (docs/05 grammar) injected on this "
                         "peer's OUTBOUND ring edge — discovered from "
                         "stats() max-tx, so no ring-order assumption — "
                         "via netem_inject before step --inject-at")
    ap.add_argument("--inject-at", type=int, default=-1)
    args = ap.parse_args()

    if args.join_delay > 0:
        time.sleep(args.join_delay)

    from pccl_tpu.comm import (
        Communicator,
        ConnectionLostError,
        KickedError,
        MasterUnreachableError,
        OperationAbortedError,
        PcclError,
        ReduceOp,
        TooFewPeersError,
    )

    # Losing the master link is now a two-tier recovery (docs/10):
    #  1. the native client transparently session-resumes against a
    #     journaled restarted master (same uuid, p2p mesh kept) — ops
    #     surface at worst a retryable ConnectionLost/Aborted;
    #  2. only when resume is impossible (no journal, budget exhausted,
    #     kicked) does the error land here and we REJOIN with a fresh
    #     communicator — the reference recipe for master restarts
    #     (docs/md/05-ImplementationNotes/03_MasterOrchestration.md).
    master_loss = (ConnectionLostError, MasterUnreachableError, KickedError)

    def build_comm(budget_s: float = 90.0):
        deadline = time.time() + budget_s
        while True:
            c = Communicator("127.0.0.1", args.master_port,
                             p2p_port=args.base_port, ss_port=args.base_port + 4,
                             bench_port=args.base_port + 8,
                             peer_group=args.peer_group)
            try:
                c.connect()
                return c
            except PcclError:
                c.destroy()
                if time.time() > deadline:
                    raise
                time.sleep(0.5)

    def rejoin(old):
        print("REJOIN", flush=True)
        try:
            old.destroy()
        except Exception:  # noqa: BLE001 — link already dead
            pass
        return build_comm()

    comm = build_comm()
    deadline = time.time() + 60
    while comm.world_size < args.min_world:
        if time.time() > deadline:
            print("TIMEOUT waiting for world", flush=True)
            return 2
        try:
            if comm.are_peers_pending():
                comm.update_topology()
        except master_loss:
            comm = rejoin(comm)
        time.sleep(0.02)

    rng = np.random.RandomState(args.seed or args.base_port)
    x = np.ones(args.count, dtype=np.float32)
    y = np.empty_like(x)
    step = 0
    last_resumes = 0
    # churn-sync lane state: offered revision + its content. Invariant the
    # whole lane hangs on: the content synced at revision R is full(R), so
    # any adopter can verify bit-correct adoption locally.
    sync_rev = 0
    w = np.zeros(max(1, args.sync_state), dtype=np.float32)
    while step < args.steps:
        if args.die_prob > 0 and rng.rand() < args.die_prob:
            print(f"DYING at step {step}", flush=True)
            import os

            os._exit(0)
        if args.die_at >= 0 and step >= args.die_at:
            # simulated crash: no destroy(), no goodbye — the master must
            # detect the dead TCP connection and abort our running ops
            print(f"DYING at step {step}", flush=True)
            sys.stdout.flush()
            import os

            os._exit(0)
        # admit pending joiners between steps (reference update-topology loop)
        try:
            if comm.are_peers_pending():
                comm.update_topology()
        except master_loss:
            comm = rejoin(comm)
            continue
        except Exception:  # noqa: BLE001 — churn mid-vote; retry next loop
            time.sleep(0.05)
            continue
        try:
            if args.quantize == "minmax":
                from pccl_tpu.comm import DataType, QuantizationAlgorithm

                info = comm.all_reduce(
                    x, y, op=ReduceOp.SUM,
                    quantization=QuantizationAlgorithm.MIN_MAX,
                    quantized_dtype=DataType.UINT8)
            else:
                info = comm.all_reduce(x, y, op=ReduceOp.SUM)
        except (KickedError, MasterUnreachableError):
            comm = rejoin(comm)
            continue
        except (ConnectionLostError, OperationAbortedError) as e:
            print(f"RETRY step={step} cause={type(e).__name__}", flush=True)
            try:
                comm.update_topology()
            except master_loss:
                comm = rejoin(comm)
            except Exception:  # noqa: BLE001
                time.sleep(0.05)
            continue
        except TooFewPeersError:
            # alone: everyone else died or left; count as progress
            y[:] = x
            info = None
        if args.sync_state > 0:
            from pccl_tpu.comm import SharedState, TensorInfo
            try:
                sinfo = comm.sync_shared_state(
                    SharedState([TensorInfo.from_numpy("w", w)],
                                revision=sync_rev))
            except (KickedError, MasterUnreachableError):
                comm = rejoin(comm)
                sync_rev = 0
                w[:] = 0
                continue
            except (ConnectionLostError, OperationAbortedError) as e:
                print(f"SYNC RETRY step={step} cause={type(e).__name__}",
                      flush=True)
                continue
            # bit-correct adoption check: whatever revision won, its
            # content must be full(revision) everywhere
            if sinfo.revision > 0 and (float(w[0]) != float(sinfo.revision)
                                       or float(w[-1]) != float(sinfo.revision)):
                print(f"WRONG SYNC step={step} rev={sinfo.revision} "
                      f"w0={w[0]}", flush=True)
                return 3
            sync_rev = sinfo.revision + 1
            w[:] = float(sync_rev)
        world = info.world_size if info is not None else 1
        tol = 1e-5 if args.quantize == "none" else 2e-2 * world
        if info is not None and abs(float(y[0]) - world) > tol:
            print(f"WRONG RESULT step={step} y={y[0]} world={world}", flush=True)
            return 3
        # surface HA session resumes (absorbed master restarts) so the
        # stress orchestrator can count resumes vs full rejoins
        try:
            rc = comm.reconnect_count
        except Exception:  # noqa: BLE001 — older lib without the attribute
            rc = 0
        if rc > last_resumes:
            print(f"RESUMED total={rc} epoch={comm.master_epoch}", flush=True)
        last_resumes = rc  # a rejoin resets the comm's counter to 0
        print(f"STEP {step} world={world} rank={args.rank}", flush=True)
        step += 1
        if args.inject_spec and step == args.inject_at:
            from pccl_tpu.comm import netem_inject

            edges = comm.stats()["edges"]
            if edges:
                ep = max(edges.items(), key=lambda kv: kv[1]["tx_bytes"])[0]
                try:
                    netem_inject(ep, args.inject_spec)
                    print(f"INJECTED {ep}", flush=True)
                except PcclError as e:
                    print(f"INJECT FAILED {e}", flush=True)
        if args.stats_every > 0 and step % args.stats_every == 0:
            import json

            try:
                print("STATS " + json.dumps(comm.stats()), flush=True)
            except Exception:  # noqa: BLE001 — mid-rejoin snapshot race
                pass
        if args.step_interval > 0:
            time.sleep(args.step_interval)
    comm.destroy()
    print("DONE", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
