"""Chaos/fault-injection test peer (subprocess worker, docs/05).

One peer of a wire_topology-emulated loopback world running a fixed number
of deterministic fp32 ring all-reduces and timing each step. The designated
victim rank injects a netem chaos fault on its OWN outbound ring edge
mid-run via ``netem_inject`` (the edge is discovered from stats() — the one
edge carrying the ring tx — so the test needs no knowledge of the ATSP ring
order). Inputs are small integers, so the fp32 ring sum is exact and the
final result must be BIT-identical whether windows traveled the direct
edge, a fresh pool connection, or a relay detour.

Prints one JSON line: per-step wall times, final-result SHA-256, and the
Communicator.stats() snapshot (watchdog/relay/dup counters included).
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--master-port", type=int, required=True)
    ap.add_argument("--rank", type=int, required=True)
    ap.add_argument("--world", type=int, required=True)
    ap.add_argument("--port-base", type=int, required=True)
    ap.add_argument("--count", type=int, default=1 << 20)
    ap.add_argument("--steps", type=int, required=True,
                    help="total collectives (warmup included)")
    ap.add_argument("--fault-at", type=int, default=-1,
                    help="victim: inject the fault BEFORE this step")
    ap.add_argument("--victim", type=int, default=0,
                    help="rank that injects on its outbound ring edge")
    ap.add_argument("--fault", default="",
                    help="chaos spec for netem_inject, e.g. "
                         "'degrade@t=0s:10mbit/60s'")
    ap.add_argument("--env", default="{}",
                    help="JSON env dict applied before the native load")
    args = ap.parse_args()

    os.environ.update(json.loads(args.env))

    import numpy as np

    from pccl_tpu.comm import Communicator, ReduceOp, netem_inject
    from pccl_tpu.comm.native_bench import _rank_ports

    p2p, ss, bench = _rank_ports(args.port_base, args.rank)
    comm = Communicator("127.0.0.1", args.master_port, p2p_port=p2p,
                        ss_port=ss, bench_port=bench)
    comm.connect()
    deadline = time.time() + 90
    while comm.world_size < args.world:
        if time.time() > deadline:
            print(json.dumps({"rank": args.rank, "error": "world timeout"}),
                  flush=True)
            return 2
        if comm.are_peers_pending():
            comm.update_topology()
        time.sleep(0.02)

    n, world = args.count, args.world
    idx = np.arange(n, dtype=np.float32)
    out = np.empty(n, dtype=np.float32)
    step_s = []
    injected = False
    for step in range(args.steps):
        if (args.rank == args.victim and args.fault and not injected
                and step == args.fault_at):
            # the outbound ring edge is the ONE p2p edge carrying our tx
            edges = comm.stats()["edges"]
            succ_ep = max(edges.items(), key=lambda kv: kv[1]["tx_bytes"])[0]
            netem_inject(succ_ep, args.fault)
            injected = True
            print(json.dumps({"rank": args.rank, "injected_on": succ_ep}),
                  flush=True)
        # small-integer inputs: the fp32 ring sum is EXACT, so results are
        # bit-identical regardless of ring order or window routing
        x = np.float32((idx + step) % 5 + (args.rank + 1))
        t0 = time.perf_counter()
        comm.all_reduce(x, out, op=ReduceOp.SUM)
        step_s.append(time.perf_counter() - t0)
        expect = world * ((idx + step) % 5) + world * (world + 1) / 2
        if not np.array_equal(out, np.float32(expect)):
            bad = int(np.argmax(out != np.float32(expect)))
            print(json.dumps({"rank": args.rank, "error":
                              f"step {step} wrong result at {bad}: "
                              f"{out[bad]} != {expect[bad]}"}), flush=True)
            return 3
    # let straggler frames of the last op's zombie sends drain into the
    # receivers' dedupe counters before snapshotting (they travel at the
    # DEGRADED rate; a bounded wait keeps conservation exact)
    time.sleep(2.0 if args.fault else 0.5)
    print(json.dumps({
        "rank": args.rank,
        "steps": step_s,
        "digest": hashlib.sha256(out.tobytes()).hexdigest(),
        "stats": comm.stats(),
    }), flush=True)
    comm.destroy()
    return 0


if __name__ == "__main__":
    sys.exit(main())
