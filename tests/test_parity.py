"""Capability-parity e2e: peer groups, quantization algos/dtypes, torch
interop, master restart + revision resume.

Reference parity targets: test_peer_groups.cpp, the quantized typed suites of
test_all_reduce.cpp, pytorch interop tests, and the checkpoint-resume
contract (revision-0 master state accepts any first revision,
ccoip_master_state.cpp:1077-1086) — SURVEY.md §4.
"""

from __future__ import annotations

import threading
import time
from pathlib import Path

import numpy as np
import pytest

LIB = Path(__file__).resolve().parent.parent / "pccl_tpu" / "native" / "build" / "libpcclt.so"
pytestmark = pytest.mark.skipif(not LIB.exists(), reason="native lib not built")

from conftest import alloc_ports as _next_port


def _spawn_peers(master_port, n, worker, base, *, peer_groups=None, min_world=None):
    """Run `worker(comm, rank)` on n threads, each with its own Communicator."""
    from pccl_tpu.comm import Communicator

    errors = []

    def peer(rank):
        try:
            comm = Communicator(
                "127.0.0.1", master_port,
                peer_group=peer_groups[rank] if peer_groups else 0,
                p2p_port=base + rank * 16, ss_port=base + rank * 16 + 4,
                bench_port=base + rank * 16 + 8)
            comm.connect()
            want = min_world if min_world is not None else n
            deadline = time.time() + 60
            while comm.global_world_size < want:
                if time.time() > deadline:
                    raise TimeoutError(f"global world never reached {want}")
                if comm.are_peers_pending():
                    comm.update_topology()
                time.sleep(0.01)
            worker(comm, rank)
            comm.destroy()
        except Exception as e:  # noqa: BLE001
            errors.append((rank, e))

    ts = [threading.Thread(target=peer, args=(r,)) for r in range(n)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=180)
    stuck = [t for t in ts if t.is_alive()]
    assert not stuck, "peer threads hung"
    assert not errors, f"peer failures: {errors}"


@pytest.fixture
def master():
    from pccl_tpu.comm import MasterNode

    m = MasterNode("0.0.0.0", _next_port())
    m.run()
    yield m
    m.interrupt()
    m.destroy()


def test_peer_groups_partition_collectives(master):
    """4 peers in 2 groups: reduces and shared state stay group-local while
    membership/attributes remain global (reference test_peer_groups.cpp)."""
    from pccl_tpu.comm import ReduceOp

    def worker(comm, rank):
        group = rank // 2
        assert comm.global_world_size == 4
        assert comm.world_size == 2          # group world
        assert comm.num_peer_groups == 2
        assert comm.largest_peer_group == 2
        # group 0 sums 1s; group 1 sums 10s — results must not mix
        val = 1.0 if group == 0 else 10.0
        x = np.full(2048, val, dtype=np.float32)
        y = np.empty_like(x)
        info = comm.all_reduce(x, y, op=ReduceOp.SUM)
        assert info.world_size == 2
        np.testing.assert_allclose(y, np.full(2048, 2 * val))

    _spawn_peers(master.port, 4, worker, base=_next_port(),
                 peer_groups=[0, 0, 1, 1])


def test_peer_groups_shared_state_independent(master):
    """Each group elects and distributes its own shared state."""
    from pccl_tpu.comm import SharedState, SharedStateSyncStrategy, TensorInfo

    def worker(comm, rank):
        group = rank // 2
        leader = rank % 2 == 0
        w = np.full(256, (group + 1) * 100.0 if leader else 0.0,
                    dtype=np.float32)
        st = SharedState([TensorInfo.from_numpy("w", w)], revision=1)
        comm.sync_shared_state(
            st, SharedStateSyncStrategy.SEND_ONLY if leader
            else SharedStateSyncStrategy.RECEIVE_ONLY)
        np.testing.assert_allclose(w, np.full(256, (group + 1) * 100.0))

    _spawn_peers(master.port, 4, worker, base=_next_port(),
                 peer_groups=[0, 0, 1, 1])


@pytest.mark.parametrize("algo,qdtype", [("minmax", "UINT8"),
                                         ("minmax", "UINT16"),
                                         ("zps", "UINT8"),
                                         ("zps", "INT8")])
def test_quantized_allreduce(master, algo, qdtype):
    """Quantized AVG all-reduce: wire bytes shrink, results stay within the
    quantization error bound, and all peers end bit-identical."""
    from pccl_tpu.comm import DataType, QuantizationAlgorithm, ReduceOp

    quant = (QuantizationAlgorithm.MIN_MAX if algo == "minmax"
             else QuantizationAlgorithm.ZERO_POINT_SCALE)
    results = {}

    def worker(comm, rank):
        rng = np.random.RandomState(rank)
        x = rng.randn(4096).astype(np.float32) + rank
        y = np.empty_like(x)
        info = comm.all_reduce(x, y, op=ReduceOp.AVG, quantization=quant,
                               quantized_dtype=getattr(DataType, qdtype))
        qsz = 1 if qdtype.endswith("8") else 2
        assert info.tx_bytes < 4096 * 4, "wire bytes did not shrink"
        assert info.tx_bytes >= 4096 * qsz // 2
        results[rank] = y.copy()

    _spawn_peers(master.port, 2, worker, base=_next_port())
    # bit parity across peers despite lossy quantization
    np.testing.assert_array_equal(results[0], results[1])
    # and close to the true mean within quantization error
    truth = (np.random.RandomState(0).randn(4096) +
             np.random.RandomState(1).randn(4096) + 1.0) / 2
    tol = 0.1 if qdtype.endswith("8") else 0.01
    np.testing.assert_allclose(results[0], truth.astype(np.float32), atol=tol)


@pytest.mark.parametrize("np_dtype,op,expected", [
    (np.int32, "SUM", 3),
    (np.float64, "MAX", 2.0),
    (np.float16, "SUM", 3.0),
    (np.uint8, "MIN", 1),
])
def test_allreduce_dtypes(master, np_dtype, op, expected):
    from pccl_tpu.comm import ReduceOp

    def worker(comm, rank):
        x = np.full(512, rank + 1, dtype=np_dtype)
        y = np.empty_like(x)
        comm.all_reduce(x, y, op=getattr(ReduceOp, op))
        np.testing.assert_allclose(y, np.full(512, expected))

    _spawn_peers(master.port, 2, worker, base=_next_port())


def test_torch_tensorinfo_shared_state(master):
    """TensorInfo.from_torch round-trips a CPU tensor through a sync."""
    torch = pytest.importorskip("torch")
    from pccl_tpu.comm import SharedState, SharedStateSyncStrategy, TensorInfo

    def worker(comm, rank):
        t = torch.full((128,), 6.0 if rank == 0 else 0.0)
        st = SharedState([TensorInfo.from_torch("t", t)], revision=1)
        comm.sync_shared_state(
            st, SharedStateSyncStrategy.SEND_ONLY if rank == 0
            else SharedStateSyncStrategy.RECEIVE_ONLY)
        assert torch.equal(t, torch.full((128,), 6.0))

    _spawn_peers(master.port, 2, worker, base=_next_port())


def test_master_restart_revision_resume():
    """The checkpoint-resume contract: a NEW master accepts whatever revision
    the reconnecting peers offer first (they resumed from a checkpoint), then
    enforces one-increment from there."""
    from pccl_tpu.comm import (MasterNode, SharedState,
                               SharedStateSyncStrategy, TensorInfo)

    port = _next_port()
    base = _next_port()

    def run_session(master, start_rev, n_syncs):
        def worker(comm, rank):
            w = np.full(64, float(start_rev), dtype=np.float32)
            for i in range(n_syncs):
                st = SharedState([TensorInfo.from_numpy("w", w)],
                                 revision=start_rev + i)
                comm.sync_shared_state(st,
                                       SharedStateSyncStrategy.ENFORCE_POPULAR)

        _spawn_peers(master.port, 2, worker, base=base)

    m1 = MasterNode("0.0.0.0", port)
    m1.run()
    try:
        run_session(m1, start_rev=5, n_syncs=2)   # revisions 5, 6
    finally:
        m1.interrupt()
        m1.destroy()

    # master "crashed"; peers resume from their checkpoint at revision 6
    m2 = MasterNode("0.0.0.0", port)
    m2.run()
    try:
        run_session(m2, start_rev=6, n_syncs=2)   # fresh master accepts 6, 7
    finally:
        m2.interrupt()
        m2.destroy()
