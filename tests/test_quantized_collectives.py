"""In-jit quantized ring all-reduce (pccl_tpu.ops.quantized_collectives).

Runs on the virtual 8-device CPU mesh (conftest). Asserts: approximation
error bounded by the blockwise int8 step, bit-identical results across
ranks (the verbatim-forward invariant), exactness on int8-represented
inputs, and shape/dtype round-trips including padding.
"""

import jax
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from pccl_tpu.ops.quantized_collectives import (quantized_pmean,
                                                quantized_ring_all_reduce)


@pytest.fixture
def mesh(eight_devices):
    return Mesh(np.array(eight_devices), ("dp",))


def _run(mesh, fn, *args):
    return jax.jit(jax.shard_map(fn, mesh=mesh, in_specs=P("dp"),
                                 out_specs=P("dp")))(*args)


def test_quantized_all_reduce_matches_psum(mesh):
    n = 8
    per = 3 * 1024 + 111  # force padding
    rng = np.random.default_rng(3)
    x = rng.standard_normal((n, per)).astype(np.float32)

    out = _run(mesh, lambda s: quantized_ring_all_reduce(s, "dp"), x)
    exact = x.sum(axis=0)
    got = np.asarray(out)
    # every rank must hold bit-identical results (verbatim forwarding)
    for r in range(1, n):
        assert np.array_equal(got[0], got[r]), f"rank {r} diverged"
    # blockwise int8 with requantized partials: error ~ sum of per-hop
    # steps; bound by a few quantization steps of the running magnitude
    scale = np.abs(x).max() / 127.0
    err = np.abs(got[0] - exact).max()
    assert err <= 16 * scale, f"err {err} vs step {scale}"


def test_quantized_all_reduce_exact_on_constant_blocks(mesh):
    # a block of constant magnitude quantizes with code ±127 and scale
    # |c|/127; choosing c as multiples of 127 keeps every scale an exact
    # fp32 integer at EVERY hop (partial sums stay multiples of 127), so
    # the constants must come through exactly
    n = 8
    per = 2048
    x = np.stack([np.full(per, 127.0 * (r + 1), dtype=np.float32)
                  for r in range(n)])
    x[3] *= -1.0  # sign coverage

    out = _run(mesh, lambda s: quantized_ring_all_reduce(s, "dp"), x)
    np.testing.assert_array_equal(np.asarray(out)[0], x.sum(axis=0))


def test_quantized_pmean_tree_and_dtype(mesh):
    n = 8
    tree = {
        "w": np.full((n, 512), 2.0, dtype=np.float32),
        "b": np.full((n, 64), -4.0, dtype=np.float32),
    }
    out = _run(mesh, lambda t: quantized_pmean(t, "dp"), tree)
    np.testing.assert_allclose(np.asarray(out["w"])[0], 2.0, rtol=0)
    np.testing.assert_allclose(np.asarray(out["b"])[0], -4.0, rtol=0)


def test_single_device_axis_is_identity():
    mesh1 = Mesh(np.array(jax.devices("cpu")[:1]), ("dp",))
    x = np.arange(100, dtype=np.float32)[None]
    out = jax.jit(jax.shard_map(
        lambda s: quantized_ring_all_reduce(s, "dp"), mesh=mesh1,
        in_specs=P("dp"), out_specs=P("dp")))(x)
    np.testing.assert_array_equal(np.asarray(out)[0], x[0])
