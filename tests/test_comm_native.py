"""End-to-end tests for pccl_tpu.comm over the native core.

Reference parity: ccoip/tests/end_to_end/test_all_reduce.cpp (real master +
N clients on loopback threads, never network mocks) and
python/tests/unit_tests/pccl_test.py (master lifecycle, communicator edge
cases)."""

import threading
import time
from pathlib import Path

import numpy as np
import pytest

LIB = Path(__file__).resolve().parent.parent / "pccl_tpu" / "native" / "build" / "libpcclt.so"
pytestmark = pytest.mark.skipif(not LIB.exists(), reason="native lib not built")

from conftest import alloc_ports


def _ports(n=1):
    return alloc_ports(64 * n)


def _run_peers(master_port, world, worker, base, host="127.0.0.1"):
    """Spin up `world` client threads; each runs worker(comm, rank).
    Mirrors the reference establishConnections helper (test_all_reduce.cpp:16-42)."""
    from pccl_tpu.comm import Communicator

    errors = []

    def peer(rank):
        comm = Communicator(host, master_port,
                            p2p_port=base + rank * 8, ss_port=base + 512 + rank * 8,
                            bench_port=base + 1024 + rank * 8)
        try:
            comm.connect()
            deadline = time.time() + 30
            while comm.world_size < world:
                if time.time() > deadline:
                    raise TimeoutError(f"rank {rank}: world never reached {world}")
                if comm.are_peers_pending():
                    comm.update_topology()
                time.sleep(0.01)
            worker(comm, rank)
        except Exception as e:  # noqa: BLE001
            errors.append((rank, e))
        finally:
            comm.destroy()

    # daemon: a wedged peer must fail the test via the liveness assert below,
    # not hang interpreter shutdown waiting on a non-daemon thread
    threads = [threading.Thread(target=peer, args=(r,), daemon=True)
               for r in range(world)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    hung = [t.name for t in threads if t.is_alive()]
    assert not hung, f"peers still running after 120s (wedged?): {hung}"
    assert not errors, f"peer failures: {errors}"


@pytest.fixture
def master():
    from pccl_tpu.comm import MasterNode

    m = MasterNode("0.0.0.0", _ports())
    m.run()
    yield m
    m.interrupt()
    m.destroy()


def test_build_info_and_master_lifecycle():
    from pccl_tpu.comm import MasterNode, _native

    lib = _native.load()
    assert b"pcclt" in lib.pccltGetBuildInfo()
    m = MasterNode("0.0.0.0", _ports())
    m.run()
    assert m.port > 0
    m.interrupt()
    m.await_termination()
    m.destroy()
    m.destroy()  # idempotent


def test_allreduce_fp32_2peer(master):
    from pccl_tpu.comm import ReduceOp

    count = 4099

    def worker(comm, rank):
        x = np.arange(count, dtype=np.float32) + rank
        y = np.empty_like(x)
        info = comm.all_reduce(x, y, op=ReduceOp.SUM, tag=7)
        expect = 2 * np.arange(count, dtype=np.float32) + 1
        np.testing.assert_allclose(y, expect, rtol=1e-6)
        assert info.world_size == 2
        assert info.tx_bytes > 0 and info.rx_bytes > 0

    _run_peers(master.port, 2, worker, base=50000)


def test_allreduce_inplace_avg_4peer(master):
    from pccl_tpu.comm import ReduceOp

    count = 1000

    def worker(comm, rank):
        x = np.full(count, float(rank), dtype=np.float32)
        comm.all_reduce(x, op=ReduceOp.AVG, tag=9)
        np.testing.assert_allclose(x, np.full(count, 1.5, dtype=np.float32),
                                   rtol=1e-6)

    _run_peers(master.port, 4, worker, base=50300)


def test_allreduce_int_dtypes(master):
    from pccl_tpu.comm import ReduceOp

    def worker(comm, rank):
        a = np.array([3, 7, 9, 2], dtype=np.int32) + rank
        comm.all_reduce(a, op=ReduceOp.MAX, tag=11)
        np.testing.assert_array_equal(a, np.array([4, 8, 10, 3], dtype=np.int32))
        b = np.array([1.5, -2.5, 4.0], dtype=np.float64) * (rank + 1)
        comm.all_reduce(b, op=ReduceOp.SUM, tag=12)
        np.testing.assert_allclose(b, np.array([4.5, -7.5, 12.0]))

    _run_peers(master.port, 2, worker, base=50600)


def test_allreduce_quantized_minmax(master):
    from pccl_tpu.comm import DataType, QuantizationAlgorithm, ReduceOp

    count = 2048

    def worker(comm, rank):
        x = np.sin(np.arange(count, dtype=np.float32) * 0.01) * 4 + rank
        comm.all_reduce(x, op=ReduceOp.SUM, tag=13,
                        quantization=QuantizationAlgorithm.MIN_MAX,
                        quantized_dtype=DataType.UINT8)
        expect = (np.sin(np.arange(count, dtype=np.float32) * 0.01) * 4) * 3 + 3
        assert np.abs(x - expect).max() < 0.2  # 8-bit wire precision

    _run_peers(master.port, 3, worker, base=50900)


def test_async_and_multiple(master):
    from pccl_tpu.comm import ReduceOp

    def worker(comm, rank):
        xs = [np.full(256, float(rank + i), dtype=np.float32) for i in range(3)]
        handles = [comm.all_reduce_async(x, tag=20 + i, op=ReduceOp.SUM)
                   for i, x in enumerate(xs)]
        for h in handles:
            h.wait()
        for i, x in enumerate(xs):
            np.testing.assert_allclose(x, np.full(256, 2 * i + 1.0))
        ys = [np.full(128, float(rank), dtype=np.float32) for _ in range(2)]
        comm.all_reduce_multiple_with_retry(ys, op=ReduceOp.SUM)
        for y in ys:
            np.testing.assert_allclose(y, np.full(128, 1.0))

    _run_peers(master.port, 2, worker, base=51200)


def test_shared_state_sync(master):
    from pccl_tpu.comm import SharedState, SharedStateSyncStrategy, TensorInfo

    def worker(comm, rank):
        w = np.full(512, 42.0 if rank == 0 else 0.0, dtype=np.float32)
        step = np.array([7 if rank == 0 else 0], dtype=np.uint64)
        state = SharedState([
            TensorInfo.from_numpy("weights", w),
            TensorInfo.from_numpy("step", step),
        ], revision=1)
        strategy = (SharedStateSyncStrategy.SEND_ONLY if rank == 0
                    else SharedStateSyncStrategy.RECEIVE_ONLY)
        info = comm.sync_shared_state(state, strategy)
        assert w[0] == 42.0 and step[0] == 7
        assert info.revision == 1
        if rank != 0:
            assert info.rx_bytes > 0

    _run_peers(master.port, 3, worker, base=51500)


def test_shared_state_popular_election(master):
    from pccl_tpu.comm import SharedState, SharedStateSyncStrategy, TensorInfo

    def worker(comm, rank):
        # ranks 0,1 agree; rank 2 diverges → popular content (0/1) wins
        w = np.full(128, 1.0 if rank < 2 else 9.0, dtype=np.float32)
        state = SharedState([TensorInfo.from_numpy("w", w)], revision=1)
        comm.sync_shared_state(state, SharedStateSyncStrategy.ENFORCE_POPULAR)
        np.testing.assert_allclose(w, np.full(128, 1.0))

    _run_peers(master.port, 3, worker, base=51800)


def test_errors():
    from pccl_tpu.comm import Communicator, MasterUnreachableError, PcclError

    comm = Communicator("127.0.0.1", 1)  # nothing listening
    with pytest.raises(MasterUnreachableError):
        comm.connect()
    comm.destroy()

    comm2 = Communicator("127.0.0.1", 2)
    with pytest.raises(PcclError):
        comm2.all_reduce(np.zeros(4, dtype=np.float32))  # not connected
    comm2.destroy()


def test_all_gather_three_peers(master):
    """Ring all-gather (pcclt extension): every peer ends with all three
    segments, ordered identically everywhere (sorted peer uuid), including
    a large multi-chunk segment size."""
    count = (1 << 20) + 77  # > CMA threshold: exercises the descriptor path
    results = {}

    def worker(comm, rank):
        x = np.full(count, float(rank + 1), dtype=np.float32)
        out, info = comm.all_gather(x)
        assert info.world_size == 3
        # own segment must sit at gather_slot
        assert float(out[comm.gather_slot][0]) == float(rank + 1)
        results[rank] = np.array(out)

    _run_peers(master.port, 3, worker, _ports(6))
    base = results[0]
    assert base.shape == (3, count)
    # all peers agree bitwise on the same ordering
    for r in (1, 2):
        assert np.array_equal(base, results[r]), f"rank {r} ordering differs"
    # the multiset of segments is exactly the three contributions
    seen = sorted(float(base[i][0]) for i in range(3))
    assert seen == [1.0, 2.0, 3.0]
    for i in range(3):
        assert np.all(base[i] == base[i][0]), "segment interior corrupted"


def test_wan_pacing_quantization_wins(master, monkeypatch):
    """The library's reason to exist: on a bandwidth-constrained wire,
    u8-ZPS quantization must beat fp32 (reference WAN pitch:
    docs/md/01_Introduction.md:8). PCCLT_WIRE_MBPS emulates a slow egress
    (process-global bucket — in-process peers share it, which preserves
    the A/B ratio); CMA/shm are force-disabled so bytes really ride the
    paced wire. Ratio-only assert: robust to host load."""
    from pccl_tpu.comm import DataType, QuantizationAlgorithm, ReduceOp

    monkeypatch.setenv("PCCLT_WIRE_MBPS", "200")  # 25 MB/s shared
    count = 1 << 20  # 4 MB fp32
    times = {}

    def run(quantize):
        def worker(comm, rank):
            rng = np.random.default_rng(3 + rank)
            x = rng.standard_normal(count).astype(np.float32)
            y = np.empty_like(x)
            kw = {}
            if quantize:
                kw = dict(quantization=QuantizationAlgorithm.ZERO_POINT_SCALE,
                          quantized_dtype=DataType.UINT8)
            comm.all_reduce(x, y, op=ReduceOp.AVG, tag=31, **kw)  # warmup
            t0 = time.perf_counter()
            for _ in range(2):
                comm.all_reduce(x, y, op=ReduceOp.AVG, tag=31, **kw)
            if rank == 0:
                times[quantize] = time.perf_counter() - t0

        _run_peers(master.port, 2, worker, _ports(4))

    run(False)
    run(True)
    speedup = times[False] / times[True]
    assert speedup > 1.8, f"quantized ring only {speedup:.2f}x faster " \
        f"(fp32 {times[False]:.2f}s vs u8 {times[True]:.2f}s) on the paced wire"


def test_wan_pacing_hierarchical_quantization_wins():
    """The hierarchical twin of test_wan_pacing_quantization_wins: on the
    BASELINE-config-4 shape (2 emulated slices, ICI mean inside each, the
    native ring across), the u8-ZPS DCN hop must beat the fp32 hop once the
    cross-slice wire is actually constrained. On unpaced loopback this A/B
    *inverts* (codec work dominates — hier2_q8_step_s > hier2_step_s in
    BENCH); the paced run is the configuration the feature was built for.
    Reference intent: /root/reference/ccoip/src/cpp/quantize.cpp:22-57."""
    from pccl_tpu.comm.native_bench import run_hierarchical_wan_bench

    # own master ports + bands (bases 25000/25400 -> derived 25000-27408),
    # clear of bench.py's 31xxx defaults so this test can run while
    # bench.py exercises the same helper
    # 2M elems at 200 Mbit/s: enough bytes that the wire dominates the u8
    # codec work on a loaded host (1M elems left the ratio within suite
    # noise of the 1.8x bar)
    r = run_hierarchical_wan_bench(elems=2 << 20, iters=2, mbps=200.0,
                                   mports=(48697, 48699),
                                   bases=(25000, 25400))
    speedup = r["hier2_wan_quant_speedup"]
    assert speedup > 1.8, (
        f"quantized DCN hop only {speedup:.2f}x faster on the paced wire "
        f"(fp32 {r['hier2_wan_step_s']:.2f}s vs u8 "
        f"{r['hier2_wan_q8_step_s']:.2f}s)")


def test_wan_rtt_windowing_wins():
    """The fat-pipe twin of test_wan_pacing_quantization_wins: on a
    high-bandwidth-delay pipe (1 Gbit/s x 50 ms RTT — PCCLT_WIRE_MBPS
    pacing + the PCCLT_WIRE_RTT_MS delivery delay line), splitting one
    reduce into concurrent windowed collectives must beat the single flow:
    a lone ring pays its stage-boundary latency stalls and consensus round
    trips serially, while the 4 concurrent windows (the most a 16 MB
    payload admits under the 1M-element window floor) overlap one
    another's stalls with drain.
    Measured 1.46-1.53x on this host at this shape; the bar is low enough
    to ride out suite load. Reference intent: concurrent reduces saturating
    the WAN (/root/reference/docs/md/01_Introduction.md:8)."""
    from pccl_tpu.comm.native_bench import run_wan_rtt_windowed_bench

    # own master ports + port bands (bases 26000/26400 -> derived
    # 26000-28408), clear of bench.py's 46xxx defaults so this test can
    # run while bench.py exercises the same helper
    r = run_wan_rtt_windowed_bench(nbytes=16 << 20, iters=2,
                                   mports=(48693, 48695),
                                   bases=(26000, 26400))
    speedup = r["wan_rtt_windowed_speedup"]
    assert speedup > 1.15, (
        f"windowed reduce only {speedup:.2f}x the single flow on the "
        f"1 Gbit x 50 ms pipe (single {r['wan_rtt_single_busbw_gbps']:.3f} "
        f"vs windowed {r['wan_rtt_windowed_busbw_gbps']:.3f} GB/s)")


def test_topology_opt_wins():
    """The reference's headline capability, proven end to end: on a
    heterogeneous emulated mesh (per-edge netem models via
    PCCLT_WIRE_*_MAP — every edge 200 Mbit/s except the pessimal 0<->1
    pair at 25 Mbit/s + 60 ms RTT, with peers joining in rank order so
    the naive ring provably crosses it), ``optimize_topology()``'s
    bandwidth probes measure the emulated edges, the ATSP solve adopts a
    ring that routes around the degraded link, and the all-reduce step
    time drops. One slow edge gates the whole lockstep ring (the premise
    of arxiv 2606.01680), so the measured win is large (~4x on this
    host); the floor is 1.25x to ride out suite load. The second
    optimize (moonshot adoption) must hold the win."""
    from pccl_tpu.comm.native_bench import run_topology_opt_bench

    # own master port + band (base 2000 -> derived 2000-4012), below every
    # other band so this test can run while bench.py exercises the same
    # helper on its 5000-7012 default band
    r = run_topology_opt_bench(master_port=48717, port_base=2000)
    speedup = r["topology_opt_speedup"]
    assert speedup > 1.25, (
        f"optimized ring only {speedup:.2f}x the naive ring on the "
        f"heterogeneous mesh (naive {r['topology_naive_step_s']:.2f}s vs "
        f"opt {r['topology_opt_step_s']:.2f}s)")
    speedup2 = r["topology_naive_step_s"] / r["topology_opt2_step_s"]
    assert speedup2 > 1.25, (
        f"second optimize (moonshot adoption) lost the win: "
        f"{speedup2:.2f}x vs first-optimize {speedup:.2f}x "
        f"(opt {r['topology_opt_step_s']:.2f}s -> "
        f"opt2 {r['topology_opt2_step_s']:.2f}s)")


def test_wire_model_map_parsing(monkeypatch):
    """Unit tests for the per-edge wire-model resolution
    (pccltWireModelQuery -> netem::Registry): exact-endpoint entries,
    bare-ip wildcard with per-field fallback, globals as defaults,
    malformed entries skipped without poisoning their neighbors, and
    per-conn refresh (env re-read on every query/connection)."""
    import ctypes

    from pccl_tpu.comm import _native

    lib = _native.load()

    def query(ip, port):
        vals = [ctypes.c_double() for _ in range(4)]
        rc = lib.pccltWireModelQuery(ip.encode(), port, *vals)
        assert rc == 0
        return tuple(v.value for v in vals)  # (mbps, rtt_ms, jitter, drop)

    # exact entry wins over wildcard; unlisted fields fall to globals
    monkeypatch.setenv("PCCLT_WIRE_MBPS_MAP",
                       "127.0.0.1:7001=25,127.0.0.1=200")
    monkeypatch.setenv("PCCLT_WIRE_RTT_MS_MAP", "127.0.0.1:7001=80")
    monkeypatch.setenv("PCCLT_WIRE_MBPS", "100")
    monkeypatch.setenv("PCCLT_WIRE_RTT_MS", "10")
    assert query("127.0.0.1", 7001) == (25.0, 80.0, 0.0, 0.0)
    # wildcard match: mbps from the ip entry, rtt from the global default
    assert query("127.0.0.1", 7002) == (200.0, 10.0, 0.0, 0.0)
    # no map match at all: the globals (legacy process-wide behavior)
    assert query("10.1.2.3", 1234) == (100.0, 10.0, 0.0, 0.0)

    # malformed entries are skipped; the valid neighbors still apply
    monkeypatch.setenv(
        "PCCLT_WIRE_MBPS_MAP",
        "garbage,=5,x=,127.0.0.1:7001=nan,127.0.0.1:7001=50, 127.0.0.1:7003=75 ,a=b=3")
    assert query("127.0.0.1", 7001)[0] == 50.0
    assert query("127.0.0.1", 7003)[0] == 75.0   # spaces trimmed
    # 'a=b=3' splits on the LAST '=': key 'a=b' is valid-but-unmatched,
    # never a crash
    assert query("10.9.9.9", 1)[0] == 100.0

    # per-conn refresh: dropping the maps reverts resolution to globals...
    monkeypatch.delenv("PCCLT_WIRE_MBPS_MAP")
    monkeypatch.delenv("PCCLT_WIRE_RTT_MS_MAP")
    assert query("127.0.0.1", 7001) == (100.0, 10.0, 0.0, 0.0)
    # ...and dropping the globals turns emulation off entirely
    monkeypatch.delenv("PCCLT_WIRE_MBPS")
    monkeypatch.delenv("PCCLT_WIRE_RTT_MS")
    assert query("127.0.0.1", 7001) == (0.0, 0.0, 0.0, 0.0)

    # jitter/drop maps resolve the same way (v6 keys carry brackets)
    monkeypatch.setenv("PCCLT_WIRE_JITTER_MS_MAP", "[::1]:7001=5")
    monkeypatch.setenv("PCCLT_WIRE_DROP_MAP", "[::1]=0.01")
    assert query("::1", 7001)[2:] == (5.0, 0.01)
    assert query("::1", 7002)[2:] == (0.0, 0.01)


def test_ipv6_loopback_reduce(master):
    """2-peer SUM all-reduce entirely over ::1: the clients dial the master
    over v6 (dual-stack listener), the master observes their v6 source
    address, distributes family-tagged v6 endpoints (PCCP/2 wire), and the
    peers' p2p data plane connects back over v6. Reference carries IPv6 in
    its inet types (ccoip_inet.h:15-29); here it routes end-to-end.

    Skips where the kernel has no v6 (ipv6.disable=1 containers): the
    listeners legitimately fall back to v4-only there by design."""
    import socket

    try:
        s = socket.socket(socket.AF_INET6, socket.SOCK_STREAM)
        s.bind(("::1", 0))
        s.close()
    except OSError:
        pytest.skip("IPv6 loopback unavailable on this host")

    def worker(comm, rank):
        x = np.full(4096, float(rank + 1), dtype=np.float32)
        comm.all_reduce(x)
        assert float(x[0]) == 3.0 and float(x[-1]) == 3.0
        assert comm.world_size == 2

    _run_peers(master.port, 2, worker, _ports(4), host="::1")


def test_wire_dtype_override_validation(master):
    """A wire-dtype override whose element size mismatches the array's must
    raise, not silently reinterpret half the buffer (element COUNT crosses
    the C ABI, not bytes)."""
    from pccl_tpu.comm import DataType

    def worker(comm, rank):
        x = np.zeros(64, dtype=np.float32)
        with pytest.raises(ValueError, match="bytes/elem"):
            comm.all_reduce(x, dtype=DataType.BFLOAT16)  # 2-byte wire, 4-byte array
        # matching override passes (uint16 bit patterns as bf16)
        y = np.full(64, 0x3F80, dtype=np.uint16)  # bf16 1.0
        comm.all_reduce(y, dtype=DataType.BFLOAT16)
        assert int(y[0]) == 0x4000  # 1.0 + 1.0 = 2.0 exactly in bf16

    _run_peers(master.port, 2, worker, _ports(4))


def test_all_gather_solo(master):
    """A solo peer's all_gather returns its own segment (docstring contract)
    instead of surfacing the native TooFewPeers rejection."""

    def worker(comm, rank):
        x = np.arange(17, dtype=np.float32)
        out, info = comm.all_gather(x)
        assert info.world_size == 1 and info.tx_bytes == 0
        assert out.shape == (1, 17)
        np.testing.assert_array_equal(out[0], x)

    _run_peers(master.port, 1, worker, _ports(4))


@pytest.mark.slow
@pytest.mark.parametrize("world", [4, 8])
def test_large_world_concurrent_soak(master, world, monkeypatch):
    """The reference's concurrent_reduce_test workload at scale (its
    main.cpp runs 12 concurrent 8M-element reduces): world 8 with 12
    in-flight tagged collectives per peer over a connection pool. This is
    the first thing that exposes SinkTable wakeup herding and master
    consensus cost at large worlds. A blowup is caught by the absolute
    per-leg ceiling below (a ratio between the two legs proved too noisy
    on a loaded 1-core host: both measurements swing with suite load).
    Values are checked exactly (integer sums in fp32 range)."""
    # pool of 4 << batch of 12: forces MultipleWithRetry's windowed launch
    # (drain-oldest at the concurrent-op cap) on every run
    monkeypatch.setenv("PCCLT_MAX_CONCURRENT_COLLECTIVE_OPS", "4")
    n_tensors, elems = 12, 8 << 20
    step_times = {}

    def worker(comm, rank):
        xs = [np.full(elems, float(rank + 1 + i), dtype=np.float32)
              for i in range(n_tensors)]
        t0 = time.perf_counter()
        comm.all_reduce_multiple_with_retry(xs)
        if rank == 0:
            step_times[world] = time.perf_counter() - t0
        base = world * (world + 1) / 2  # sum of (rank+1)
        for i, x in enumerate(xs):
            assert float(x[0]) == base + world * i, \
                f"tensor {i}: {x[0]} != {base + world * i}"
            assert float(x[-1]) == base + world * i

    _run_peers(master.port, world, worker, _ports(world * 8))
    # per-byte floor instead of a wall-clock ceiling: the step moves
    # 2(N-1)/N * 384 MB of logical gradient per peer; healthy runs sustain
    # 0.03+ GB/s effective even with the full suite loading this 1-core
    # host (unloaded: 0.15-0.3), so the floor catches a real scaling
    # regression (wakeup herding, consensus stalls) rather than only total
    # collapse. The same workload is measured on a quiet host as
    # soak8_step_s in BENCH extra (native_bench.run_soak_bench).
    # floor 0.02 = the documented worst healthy loaded run (20 s at world 4
    # ≈ 0.03 GB/s) with ~1.5x margin; unloaded runs sustain 0.15-0.3
    logical_gb = 2 * (world - 1) / world * n_tensors * elems * 4 / 1e9
    eff = logical_gb / step_times[world]
    assert eff > 0.02, (
        f"world-{world} soak effective busbw {eff:.3f} GB/s "
        f"({step_times[world]:.1f} s for {logical_gb:.2f} GB)")
