"""Registered shared-memory buffer tests (pccltShmAlloc / shm_ndarray).

pcclt extension: buffers allocated through the shm registry take the
same-host ZERO-copy collective path — peers map the owner's memfd region
(announced over the data conn) and reduce straight out of it. The reference
(jundi69/pccl) has no registered-buffer concept; these tests assert the
pcclt-specific contract: bit-identical results vs ordinary buffers, safe
mixing of registered and unregistered peers, and retire-on-free semantics.
"""

import gc
import threading
import time
from pathlib import Path

import numpy as np
import pytest

LIB = Path(__file__).resolve().parent.parent / "pccl_tpu" / "native" / "build" / "libpcclt.so"
pytestmark = pytest.mark.skipif(not LIB.exists(), reason="native lib not built")

from conftest import alloc_ports


def _ports(n=1):
    return alloc_ports(64 * n)


@pytest.fixture
def master():
    from pccl_tpu.comm import MasterNode

    m = MasterNode("0.0.0.0", _ports())
    m.run()
    yield m
    m.interrupt()
    m.destroy()


def _run_peers(master_port, world, worker, base):
    from pccl_tpu.comm import Communicator

    errors = []

    def peer(rank):
        comm = Communicator("127.0.0.1", master_port,
                            p2p_port=base + rank * 8, ss_port=base + 512 + rank * 8,
                            bench_port=base + 1024 + rank * 8)
        try:
            comm.connect()
            deadline = time.time() + 30
            while comm.world_size < world:
                if time.time() > deadline:
                    raise TimeoutError(f"rank {rank}: world never reached {world}")
                if comm.are_peers_pending():
                    comm.update_topology()
                time.sleep(0.01)
            worker(comm, rank)
        except Exception as e:  # noqa: BLE001
            errors.append((rank, e))
        finally:
            comm.destroy()

    threads = [threading.Thread(target=peer, args=(r,)) for r in range(world)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not errors, f"peer failures: {errors}"


def test_shm_ndarray_alloc_rw_free():
    from pccl_tpu.comm import _native
    from pccl_tpu.comm.api import shm_ndarray

    lib = _native.load()
    a = shm_ndarray((256, 33), np.float64)
    assert a.shape == (256, 33) and a.dtype == np.float64
    a[:] = 7.5
    assert float(a.sum()) == 7.5 * 256 * 33
    # int shape form + dtype default
    b = shm_ndarray(100)
    assert b.shape == (100,) and b.dtype == np.float32
    del a, b
    gc.collect()
    # double free through the C API must be rejected, not crash
    import ctypes

    assert lib.pccltShmFree(ctypes.c_void_p(0)) != 0


# count > CMA threshold (64 KiB) so the descriptor/zero-copy path engages
COUNT = (1 << 20) + 173


def test_allreduce_shm_both_peers(master):
    from pccl_tpu.comm import ReduceOp
    from pccl_tpu.comm.api import shm_ndarray

    rng = np.random.default_rng(7)
    inputs = [rng.standard_normal(COUNT).astype(np.float32) for _ in range(2)]
    expect = inputs[0] + inputs[1]
    results = {}

    def worker(comm, rank):
        x = shm_ndarray(COUNT, np.float32)
        x[:] = inputs[rank]
        y = shm_ndarray(COUNT, np.float32)
        for _ in range(3):  # repeat: sink reuse + announce dedup
            comm.all_reduce(x, y, op=ReduceOp.SUM)
        results[rank] = np.array(y)

    _run_peers(master.port, 2, worker, _ports(4))
    for r in range(2):
        np.testing.assert_allclose(results[r], expect, rtol=1e-6)
    assert np.array_equal(results[0], results[1]), "peers must agree bitwise"


def test_allreduce_mixed_registered_unregistered(master):
    from pccl_tpu.comm import ReduceOp
    from pccl_tpu.comm.api import shm_ndarray

    rng = np.random.default_rng(11)
    inputs = [rng.standard_normal(COUNT).astype(np.float32) for _ in range(2)]
    expect = inputs[0] + inputs[1]
    results = {}

    def worker(comm, rank):
        if rank == 0:  # registered sender, plain receiver buffers
            x = shm_ndarray(COUNT, np.float32)
            x[:] = inputs[rank]
            y = np.empty(COUNT, np.float32)
        else:  # plain buffers: peer falls back to the pull path
            x = inputs[rank].copy()
            y = np.empty(COUNT, np.float32)
        comm.all_reduce(x, y, op=ReduceOp.SUM)
        results[rank] = np.array(y)

    _run_peers(master.port, 2, worker, _ports(4))
    for r in range(2):
        np.testing.assert_allclose(results[r], expect, rtol=1e-6)


def test_shm_in_place_and_avg(master):
    from pccl_tpu.comm import ReduceOp
    from pccl_tpu.comm.api import shm_ndarray

    rng = np.random.default_rng(13)
    inputs = [rng.standard_normal(COUNT).astype(np.float32) for _ in range(2)]
    expect = (inputs[0] + inputs[1]) / 2.0
    results = {}

    def worker(comm, rank):
        x = shm_ndarray(COUNT, np.float32)
        x[:] = inputs[rank]
        comm.all_reduce(x, x, op=ReduceOp.AVG)  # in-place
        results[rank] = np.array(x)

    _run_peers(master.port, 2, worker, _ports(4))
    for r in range(2):
        np.testing.assert_allclose(results[r], expect, rtol=1e-6)


def test_shm_free_retires_then_fresh_buffer_works(master):
    """Free a registered buffer between ops: the retire must propagate and a
    fresh buffer (possibly at a new address) must still reduce correctly."""
    from pccl_tpu.comm import ReduceOp
    from pccl_tpu.comm.api import shm_ndarray

    results = {}

    def worker(comm, rank):
        x = shm_ndarray(COUNT, np.float32)
        x[:] = float(rank + 1)
        y = shm_ndarray(COUNT, np.float32)
        comm.all_reduce(x, y, op=ReduceOp.SUM)
        assert float(y[0]) == 3.0
        del x
        gc.collect()  # frees + queues the retire for every conn
        x2 = shm_ndarray(COUNT, np.float32)
        x2[:] = float(10 * (rank + 1))
        comm.all_reduce(x2, y, op=ReduceOp.SUM)
        results[rank] = float(y[0])

    _run_peers(master.port, 2, worker, _ports(4))
    assert results[0] == results[1] == 30.0


def test_shm_quantized_allreduce(master):
    """Quantized path with registered buffers: the quantized wire bytes are
    produced into ordinary scratch, so this exercises registered send +
    unregistered scratch in one op."""
    from pccl_tpu.comm import DataType, QuantizationAlgorithm, ReduceOp
    from pccl_tpu.comm.api import shm_ndarray

    results = {}

    def worker(comm, rank):
        x = shm_ndarray(COUNT, np.float32)
        x[:] = np.linspace(0.0, 1.0, COUNT, dtype=np.float32) + rank
        y = shm_ndarray(COUNT, np.float32)
        comm.all_reduce(x, y, op=ReduceOp.SUM,
                        quantization=QuantizationAlgorithm.MIN_MAX,
                        quantized_dtype=DataType.UINT8)
        results[rank] = np.array(y)

    _run_peers(master.port, 2, worker, _ports(4))
    assert np.array_equal(results[0], results[1]), "bit parity across peers"
    expect = np.linspace(0.0, 1.0, COUNT, dtype=np.float32) * 2 + 1
    np.testing.assert_allclose(results[0], expect, atol=2e-2)


def test_windowed_avg_reduce(master):
    """avg_all_reduce_windowed splits into concurrent tagged collectives
    (reference MultipleWithRetry recipe); result must equal the single-op
    mean bitwise across peers."""
    from pccl_tpu.comm.api import shm_ndarray
    from pccl_tpu.parallel.ring import avg_all_reduce_windowed

    n = (2 << 20) + 577  # two windows and a ragged tail
    rng = np.random.default_rng(17)
    inputs = [rng.standard_normal(n).astype(np.float32) for _ in range(2)]
    expect = (inputs[0] + inputs[1]) / 2.0
    results = {}

    def worker(comm, rank):
        vec = shm_ndarray(n, np.float32)
        vec[:] = inputs[rank]
        world = avg_all_reduce_windowed(comm, vec, windows=2)
        assert world == 2
        results[rank] = np.array(vec)

    _run_peers(master.port, 2, worker, _ports(4))
    assert np.array_equal(results[0], results[1])
    np.testing.assert_allclose(results[0], expect, rtol=1e-6)


def test_pure_tcp_path_cma_disabled(master):
    """PCCLT_CMA=0 forces the WAN wire path (chunked TCP streaming into
    registered sinks, no same-host shortcuts) even on loopback — the ring
    must produce correct results there too. This is the only loopback-CI
    coverage the real cross-host path gets."""
    import os

    from test_fault_tolerance import PeerProc

    base = _ports(4)
    env = {**os.environ, "PCCLT_CMA": "0"}
    peers = [PeerProc(master.port, r, base + r * 16, env=env, steps=6,
                      min_world=2, count=(4 << 20) // 4 + 333)  # multi-chunk
             for r in range(2)]
    try:
        for p in peers:
            assert p.join() == 0, f"pure-TCP peer failed: {p.lines[-10:]}"
            assert p.wait_for_step(5), f"did not finish: {p.lines[-5:]}"
    finally:
        for p in peers:
            p.kill()
