"""Fleet-scale observability plane (docs/09, the N=1000 gate).

The master must survive a metropolis worth of telemetry: ingest lands on a
bounded queue drained OFF the dispatcher thread, /metrics stays bounded-
cardinality (top-K edge detail + per-peer rollups), and one scrape of the
steady-state N=1000 surface completes inside a Prometheus scrape window.
The flood comes from ``pccltDigestFlood`` — native observer sessions
(PCCP/2 hello tail byte) that push digests but never join the world.

Tiers here:
  * promlint self-checks — the strict exposition-text validator must
    catch the classes of breakage it exists for (it gates every scrape
    in this file AND test_observability.py);
  * moderate-N ingest/rollup/history end-to-end on a real master
    subprocess (per-PR lane);
  * the full N=1000 gate via run_master_scale_bench (slow lane; hard
    thresholds mirrored in ci.yml's fleet-scale job).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import sys
import time
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
LIB = REPO / "pccl_tpu" / "native" / "build" / "libpcclt.so"
pytestmark = pytest.mark.skipif(not LIB.exists(), reason="native lib not built")

from conftest import alloc_ports  # noqa: E402


def _lib_or_skip():
    from pccl_tpu.comm import _native

    lib = _native.load()
    if not hasattr(lib, "pccltDigestFlood"):
        pytest.skip("libpcclt.so predates the fleet-scale bench hooks")
    return lib


# ------------------------------------------------------------- promlint


def test_promlint_accepts_valid_exposition():
    from pccl_tpu.comm import promlint

    text = (
        "# HELP pcclt_up whether up\n"
        "# TYPE pcclt_up gauge\n"
        'pcclt_up{peer="a",group="0"} 1\n'
        'pcclt_up{peer="b\\"x\\\\y\\n",group="0"} 0\n'
        "# TYPE pcclt_lat_seconds histogram\n"
        'pcclt_lat_seconds_bucket{le="0.1"} 2\n'
        'pcclt_lat_seconds_bucket{le="+Inf"} 3\n'
        "pcclt_lat_seconds_sum 0.5\n"
        "pcclt_lat_seconds_count 3\n")
    assert promlint.lint(text) == []


@pytest.mark.parametrize("mutation,needle", [
    # family's samples torn apart by another family's sample
    ('pcclt_a 1\npcclt_b 2\npcclt_a{x="1"} 3\n', "reopened"),
    # same series twice
    ('pcclt_a{x="1"} 1\npcclt_a{x="1"} 2\n', "duplicate series"),
    # label value never closes its quote
    ('pcclt_a{x="oops} 1\n', "unterminated"),
    # garbage where a float should be
    ("pcclt_a one\n", "bad value"),
    # histogram counts must be monotone in le
    ("# TYPE pcclt_h histogram\n"
     'pcclt_h_bucket{le="0.1"} 5\npcclt_h_bucket{le="1"} 3\n'
     'pcclt_h_bucket{le="+Inf"} 5\npcclt_h_sum 1\npcclt_h_count 5\n',
     "non-monotone"),
    # +Inf bucket must equal _count
    ("# TYPE pcclt_h histogram\n"
     'pcclt_h_bucket{le="+Inf"} 4\npcclt_h_sum 1\npcclt_h_count 5\n',
     "!= _count"),
    # buckets with no +Inf terminal
    ("# TYPE pcclt_h histogram\n"
     'pcclt_h_bucket{le="1"} 4\npcclt_h_sum 1\npcclt_h_count 4\n',
     "missing +Inf"),
])
def test_promlint_rejects_malformed(mutation, needle):
    from pccl_tpu.comm import promlint

    errs = promlint.lint(mutation)
    assert any(needle in e for e in errs), (needle, errs)


# ------------------------------------------------- moderate-N end-to-end


class _Master:
    def __init__(self, port: int, mport: int, env: dict | None = None):
        e = {**os.environ, "PCCLT_METRICS_MAX_AGE_MS": "0", **(env or {})}
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "pccl_tpu.comm.master",
             "--port", str(port), "--metrics-port", str(mport)],
            cwd=str(REPO), env=e, stdout=subprocess.DEVNULL,
            stderr=subprocess.STDOUT)
        self.mport = mport
        from pccl_tpu.comm.native_bench import _scrape_http

        deadline = time.time() + 30
        while time.time() < deadline:
            try:
                _scrape_http(mport, "/health", timeout=1)
                return
            except OSError:
                if self.proc.poll() is not None:
                    raise RuntimeError("master died on startup")
                time.sleep(0.05)
        raise RuntimeError("master never served /health")

    def scrape(self, path: str = "/metrics") -> str:
        from pccl_tpu.comm.native_bench import _scrape_http

        text = _scrape_http(self.mport, path)
        if path.startswith("/metrics"):
            from pccl_tpu.comm import promlint

            promlint.assert_valid(text, context=f"GET {path}")
        return text

    def kill(self):
        if self.proc.poll() is None:
            self.proc.kill()
        self.proc.wait(timeout=10)


def _flood(lib, port: int, peers: int, edges: int, hz: float, secs: float,
           threads: int = 4) -> int:
    sent = ctypes.c_uint64(0)
    wall = ctypes.c_double(0.0)
    rc = lib.pccltDigestFlood(b"127.0.0.1", port, peers, edges, hz, secs,
                              threads, ctypes.byref(sent), ctypes.byref(wall))
    assert rc == 0, f"pccltDigestFlood rc={rc}"
    return sent.value


def test_fleet_ingest_topk_and_history():
    """80 observers x 4 edges (320 edges > the default top-K of 64): every
    digest folds with zero queue drops, observers never enter the world,
    /metrics stays promlint-clean with rollup families carrying the
    overflow, TOPK=0 restores the full per-edge surface, and the /health
    history ring keeps bounded, aging samples."""
    import json

    lib = _lib_or_skip()
    base = alloc_ports(4)
    m = _Master(base, base + 1, env={"PCCLT_HEALTH_HISTORY_MS": "50",
                                     "PCCLT_HEALTH_HISTORY": "6"})
    try:
        peers, edges = 80, 4
        sent = _flood(lib, base, peers, edges, hz=6.0, secs=1.5)
        assert sent >= peers  # at least one full round landed

        # drain: accepted == folded, drops == 0
        deadline = time.time() + 30
        while time.time() < deadline:
            text = m.scrape()
            folded = _prom(text, "pcclt_master_telemetry_digests_total")
            if folded >= sent:
                break
            time.sleep(0.1)
        assert folded == sent, (folded, sent)
        assert _prom(text, "pcclt_master_digest_queue_dropped_total") == 0
        assert _prom(text, "pcclt_master_digest_queue_capacity") > 0
        # fold latency histogram present and coherent (promlint already
        # proved +Inf == count)
        assert "pcclt_master_digest_fold_seconds_bucket{" in text

        # observers are telemetry-only: the world stayed empty
        health = json.loads(m.scrape("/health"))
        assert health["world_size"] == 0
        assert health["telemetry_digests"] == sent
        assert "build" in health

        # bounded cardinality: 320 edges, only top-64 in detail; the rest
        # rolled up per reporting peer, conservation across the split
        n_detail = sum(1 for ln in text.splitlines()
                       if ln.startswith("pcclt_edge_tx_bytes_total{"))
        assert n_detail == 64
        rollup = _prom_sum(text, "pcclt_peer_edges_rolled_up")
        assert n_detail + rollup == peers * edges
        assert _prom_sum(text, "pcclt_edge_tx_bytes_total") > 0
        assert _prom_sum(text, "pcclt_peer_rollup_tx_bytes_total") > 0

        # /health history: bounded ring of aging samples
        time.sleep(0.4)
        hist = json.loads(m.scrape("/health?history=1"))["history"]
        assert 2 <= len(hist) <= 6
        assert all("age_ms" in s and "digest_rate" in s for s in hist)
        assert "history" not in json.loads(m.scrape("/health"))
    finally:
        m.kill()


def test_fleet_topk_zero_restores_full_surface():
    """A master spawned with PCCLT_METRICS_EDGE_TOPK=0 exposes every edge
    as full per-edge series and emits no rollup families."""
    lib = _lib_or_skip()
    base = alloc_ports(4)
    m = _Master(base, base + 1, env={"PCCLT_METRICS_EDGE_TOPK": "0"})
    try:
        peers, edges = 40, 4
        sent = _flood(lib, base, peers, edges, hz=5.0, secs=1.0)
        deadline = time.time() + 30
        while time.time() < deadline:
            text = m.scrape()
            if _prom(text, "pcclt_master_telemetry_digests_total") >= sent:
                break
            time.sleep(0.1)
        n_detail = sum(1 for ln in text.splitlines()
                       if ln.startswith("pcclt_edge_tx_bytes_total{"))
        assert n_detail == peers * edges
        assert "pcclt_peer_edges_rolled_up" not in text
        assert "pcclt_peer_rollup_tx_bytes_total" not in text
    finally:
        m.kill()


def _prom(text: str, name: str) -> float:
    for line in text.splitlines():
        if line.startswith(name + " "):
            return float(line.rsplit(None, 1)[-1])
    raise AssertionError(f"{name} not in scrape")


def _prom_sum(text: str, name: str) -> float:
    return sum(float(ln.rsplit(None, 1)[-1]) for ln in text.splitlines()
               if ln.startswith(name + "{"))


# ------------------------------------------------------ the N=1000 gate


@pytest.mark.slow
def test_fleet_full_scale_gate():
    """ISSUE-17 acceptance: 1000 observers x 8 edges at ~12 Hz. Hard
    gates (mirrored in ci.yml's fleet-scale lane): zero ingest-queue
    drops, >= 10k digests/s accepted, the bounded top-K scrape under 1 s,
    promlint-clean, and journal replay of 1000 client records under 5 s."""
    _lib_or_skip()
    from pccl_tpu.comm.native_bench import run_master_scale_bench

    r = run_master_scale_bench(peers=1000, edges=8, hz=12.0, seconds=4.0,
                               threads=8, master_port=alloc_ports(4))
    assert r["master_scale_digest_drops"] == 0, r
    assert r["master_scale_ingest_rate"] >= 10_000, r
    assert r["master_scale_scrape_s"] < 1.0, r
    assert r["master_scale_promlint_violations"] == 0, r
    assert r["master_scale_digests_folded"] >= r["master_scale_digests_sent"]
    assert r["master_scale_replay_s"] < 5.0, r
    # the dispatcher stayed responsive mid-flood: /health under 250 ms
    assert r["master_scale_health_flood_s"] < 0.25, r
    # the paired A/B: admission (observer hello -> welcome on the
    # dispatcher thread) unchanged with the flood on — the enqueue-only
    # ingest path must never put fold work on the admission critical path.
    # Absolute bound, not a ratio: quiet-side round trips are tens of µs,
    # so a ratio gate would amplify scheduler noise into flakes.
    assert r["master_scale_admission_flood_p99_s"] < 0.05, r
