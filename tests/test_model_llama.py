"""Llama model family: GQA + SwiGLU decoder on the shared TPU substrate."""

import jax
import jax.numpy as jnp
import numpy as np

from pccl_tpu.models import llama


def test_forward_shapes_and_gqa():
    cfg = llama.tiny_config()
    assert cfg.n_kv_head < cfg.n_head  # the grouped path is actually exercised
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    # kv projection is sized for the GROUPED heads, not the full head count
    kv = cfg.n_kv_head * cfg.head_dim
    assert params["attn_kv"].shape == (cfg.n_layer, cfg.n_embd, 2 * kv)
    tokens = jnp.zeros((2, 16), dtype=jnp.int32)
    logits = llama.forward_jit(params, tokens, cfg)
    assert logits.shape == (2, 16, cfg.vocab_size)
    assert np.isfinite(np.asarray(logits)).all()


def test_loss_decreases_one_sgd_step():
    cfg = llama.tiny_config()
    params = llama.init_params(jax.random.PRNGKey(1), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(2), (4, 32), 0, cfg.vocab_size)
    targets = jnp.roll(tokens, -1, axis=1)
    loss0, grads = jax.value_and_grad(llama.loss_fn)(params, tokens, targets, cfg)
    params2 = jax.tree.map(lambda p, g: p - 0.1 * g, params, grads)
    loss1 = llama.loss_fn(params2, tokens, targets, cfg)
    assert float(loss1) < float(loss0)


def test_causality():
    cfg = llama.tiny_config()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    t1 = jnp.zeros((1, 8), dtype=jnp.int32)
    t2 = t1.at[0, 7].set(3)
    l1 = llama.forward(params, t1, cfg)
    l2 = llama.forward(params, t2, cfg)
    np.testing.assert_allclose(np.asarray(l1[0, :7]), np.asarray(l2[0, :7]),
                               atol=1e-5)


def test_gqa_equals_mha_with_tiled_kv_weights():
    """Grouped-query semantics: a GQA model must equal a FULL-head model
    whose k/v projection weights are the grouped weights tiled across each
    head group (repeating activations after projection == projecting with
    repeated weights). A swapped k/v split, wrong head-major reshape, or
    wrong repeat axis all break this wholesale."""
    cfg_g = llama.tiny_config()                       # Hkv=2 < H=4
    cfg_f = llama.tiny_config(n_kv_head=cfg_g.n_head)  # plain MHA
    params = llama.init_params(jax.random.PRNGKey(3), cfg_g)
    H, Hkv, Dh = cfg_g.n_head, cfg_g.n_kv_head, cfg_g.head_dim
    kw, vw = np.split(np.asarray(params["attn_kv"]), 2, axis=-1)

    def tile(w):  # [L, d, Hkv*Dh] -> [L, d, H*Dh], repeating per head group
        L, d, _ = w.shape
        return np.repeat(w.reshape(L, d, Hkv, Dh), H // Hkv,
                         axis=2).reshape(L, d, H * Dh)

    params_f = dict(params)
    params_f["attn_kv"] = jnp.asarray(np.concatenate([tile(kw), tile(vw)], -1))
    tokens = jax.random.randint(jax.random.PRNGKey(4), (2, 12), 0,
                                cfg_g.vocab_size)
    out_g = np.asarray(llama.forward(params, tokens, cfg_g))
    out_f = np.asarray(llama.forward(params_f, tokens, cfg_f))
    np.testing.assert_allclose(out_g, out_f, rtol=2e-2, atol=2e-2)
    assert np.mean(np.abs(out_g - out_f)) < 1e-3  # same math, bf16 noise only


def test_tensor_parallel_forward(eight_devices):
    """tp-sharded params produce the same logits as replicated ones — the
    LLAMA_PARAM_SPECS layouts must be consistent with the model's contraction
    dims (a wrong spec changes results or fails to lower)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from pccl_tpu.parallel import mesh as mesh_lib

    cfg = llama.tiny_config()
    params = llama.init_params(jax.random.PRNGKey(5), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(6), (4, 16), 0, cfg.vocab_size)
    ref = np.asarray(llama.forward_jit(params, tokens, cfg))

    mesh = mesh_lib.make_mesh(eight_devices, axis_names=("dp", "tp"), shape=(4, 2))
    shardings = mesh_lib.llama_param_sharding(mesh)
    sharded = {k: jax.device_put(v, shardings[k]) for k, v in params.items()}
    tok_sh = jax.device_put(tokens, NamedSharding(mesh, P("dp", None)))
    out = np.asarray(llama.forward_jit(sharded, tok_sh, cfg))
    # bf16 + different contraction order across shardings: compare loosely
    # elementwise and tightly in aggregate (a wrong PartitionSpec produces
    # wholesale garbage, not 1e-2-scale noise)
    np.testing.assert_allclose(out, ref, rtol=0.1, atol=0.1)
    # measured bf16 noise on this shape: mean |diff| ~0.007 on logits of
    # ~0.8 mean magnitude; wholesale-garbage specs land orders above this
    assert np.mean(np.abs(out - ref)) < 0.03


def test_named_configs():
    c = llama.named_config("8b")
    assert (c.n_layer, c.n_head, c.n_kv_head, c.n_embd) == (32, 32, 8, 4096)
    c2 = llama.named_config("tiny", block_size=64)
    assert c2.block_size == 64


def test_chunked_ce_matches_full():
    """Chunked CE (models/_common.py:chunked_ce_loss) parity for the llama
    family — loss and grads match the full-logits path."""
    import jax
    import numpy as np

    from pccl_tpu.models import llama

    cfg = llama.tiny_config()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    tok = jax.random.randint(jax.random.PRNGKey(1), (2, cfg.block_size),
                             0, cfg.vocab_size)

    def lg(chunk):
        return jax.jit(jax.value_and_grad(
            lambda p: llama.loss_fn(p, tok, tok, cfg, None, False,
                                    chunk)))(params)

    l0, g0 = lg(None)
    l1, g1 = lg(cfg.block_size // 4)
    np.testing.assert_allclose(float(l1), float(l0), rtol=2e-5)
    # non-head leaves are bit-identical; the head grad differs by bf16
    # accumulation order (chunked partial sums vs one big matmul)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=2e-2, atol=5e-4), g0, g1)
