"""End-to-end: the 2D-grid (FSDP × PCCL) example over a real master.

Reference parity: the grid pattern of /root/reference/python/examples/
nanogpt_diloco/sync_diloco_fsdp.py and the footguns doc
(/root/reference/docs/md/8_CommonFootguns.md:4-100) — peer group = shard
index, grid-fullness gate, reduced fault tolerance caveat. Cells are OS
processes on loopback; each runs a 2-device virtual CPU mesh (intra-cell
tensor sharding), so the full composition — in-mesh XLA collectives ×
per-shard TCP rings × mapped-file column exchange — is exercised.
"""

from __future__ import annotations

import os
import subprocess
import sys
import time
from pathlib import Path

import numpy as np
import pytest

REPO = Path(__file__).resolve().parent.parent
LIB = REPO / "pccl_tpu" / "native" / "build" / "libpcclt.so"
SCRIPT = REPO / "examples" / "grid_fsdp" / "grid_diloco.py"
pytestmark = pytest.mark.skipif(not LIB.exists(), reason="native lib not built")

from conftest import alloc_ports as _next_port


def _cell_env() -> dict:
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env["JAX_PLATFORMS"] = "cpu"
    return env


def _spawn_cell(master_port: int, shard: int, base_port: int,
                grid_file: str, num_shards: int = 2, min_replicas: int = 1,
                outer_steps: int = 4, extra: list[str] = ()) -> subprocess.Popen:
    cmd = [sys.executable, str(SCRIPT),
           "--master-port", str(master_port),
           "--num-shards", str(num_shards), "--peer-group", str(shard),
           "--base-port", str(base_port), "--grid-file", grid_file,
           "--min-replicas", str(min_replicas),
           "--outer-steps", str(outer_steps),
           "--inner-steps", "4", "--batch", "4", "--block", "32",
           # 4 cells cold-start jax on one loaded core: joining can take
           # minutes of wall, so the world-wait must outlast it
           "--connect-timeout", "600",
           *extra]
    return subprocess.Popen(cmd, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True,
                            env=_cell_env())


def _finish(proc: subprocess.Popen, timeout: float = 420) -> str:
    out, _ = proc.communicate(timeout=timeout)
    assert proc.returncode == 0, f"grid cell failed:\n{out[-3000:]}"
    return out


def _final_losses(out: str):
    for ln in out.splitlines():
        if ln.startswith("FINAL first"):
            parts = dict(kv.split("=") for kv in ln.split()[1:])
            return float(parts["first_loss"]), float(parts["last_loss"])
    raise AssertionError(f"no FINAL line:\n{out[-3000:]}")


@pytest.fixture
def master():
    from pccl_tpu.comm import MasterNode

    m = MasterNode("0.0.0.0", _next_port())
    m.run()
    yield m
    m.interrupt()
    m.destroy()


@pytest.fixture
def grid_file(tmp_path):
    return str(tmp_path / "grid.bin")


def test_grid_2x2_trains(master, grid_file):
    """Full rectangular grid: 2 shard groups × 2 replicas. Every cell must
    see the complete grid, train, and end at the same revision."""
    base = _next_port(span=16 * 4)
    procs = [_spawn_cell(master.port, g, base + (g * 2 + r) * 16, grid_file,
                         min_replicas=2)
             for g in (0, 1) for r in (0, 1)]
    try:
        outs = [_finish(p) for p in procs]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for out in outs:
        first, last = _final_losses(out)
        assert last < first
        assert "grid 2x2 global 4" in out  # the full grid actually formed


def test_grid_late_column_join(master, grid_file):
    """A second replica column joins mid-run: the grid gate holds the run
    open until BOTH cells of the new column are admitted (the footgun this
    pattern exists for), then joiners adopt the group's shard + revision and
    everyone terminates at the same revision."""
    base = _next_port(span=16 * 4)
    incumbents = [_spawn_cell(master.port, g, base + g * 16, grid_file,
                              outer_steps=6) for g in (0, 1)]
    time.sleep(12)  # incumbents make progress as a 2x1 grid first
    joiners = [_spawn_cell(master.port, g, base + (2 + g) * 16, grid_file,
                           outer_steps=6) for g in (0, 1)]
    procs = incumbents + joiners
    try:
        outs = [_finish(p) for p in procs]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for out in outs:
        first, last = _final_losses(out)
        assert last < first
    # the joined grid was observed rectangular at width 2 by some cell
    assert any("grid 2x2 global 4" in out for out in outs)


def test_grid_survives_killed_column(master, grid_file):
    """SIGKILL an entire replica column mid-run — the grid's failure unit
    (footguns doc: a dead GPU takes its whole FSDP column down). Once the
    master kicks the dead cells the grid is rectangular at width 1 again;
    each group's ring retries down to its survivor and column 0 finishes."""
    base = _next_port(span=16 * 4)
    procs = [_spawn_cell(master.port, g, base + (g * 2 + r) * 16, grid_file,
                         min_replicas=2, outer_steps=6)
             for g in (0, 1) for r in (0, 1)]
    victims = [procs[3], procs[1]]  # column r=1: cells (1,1) and (0,1)
    survivors = [procs[0], procs[2]]
    try:
        # kill only once the grid actually formed and finished an outer
        # step — the grid file's sequence header says so (jax cold-start
        # of 4 cells on one loaded core can take minutes)
        deadline = time.time() + 360
        while time.time() < deadline:
            try:
                # [magic, G, count, seq0, seq1] — GridFile._HDR = 3
                hdr = np.fromfile(grid_file, dtype=np.int64, count=5)
                if len(hdr) == 5 and (hdr[3:] >= 1).all():
                    break
            except (FileNotFoundError, OSError):
                pass
            time.sleep(0.5)
        for v in victims:
            v.kill()
        outs = [_finish(p, timeout=600) for p in survivors]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for out in outs:
        first, last = _final_losses(out)
        assert last < first


def _grid_file_cls():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "grid_diloco", REPO / "examples" / "grid_fsdp" / "grid_diloco.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.GridFile


def test_grid_file_lifecycle(tmp_path):
    """GridFile guarantees: atomic init with -1 sentinels, publish/wait
    ordering, attach-compatible, LOUD rejection of incompatible stale
    files (a silent attach would hand a new run another run's params)."""
    GridFile = _grid_file_cls()
    path = str(tmp_path / "g.bin")
    g = GridFile(path, 2, 100)
    assert list(g.seq) == [-1, -1]
    data = np.arange(50, dtype=np.float32)
    g.publish(0, 3, data)
    assert g.seq[0] == 3 and g.seq[1] == -1
    # same-shape attacher sees the published shard
    h = GridFile(path, 2, 100)
    np.testing.assert_array_equal(h.read_full()[:50], data)
    h.publish(1, 3, np.zeros(50, np.float32))
    g.wait_all(3, timeout=5)
    # wrong size -> loud error, never a misaligned attach
    with pytest.raises(RuntimeError, match="grid file"):
        GridFile(path, 2, 200)
    # same byte size (8·(3+4)+4·96 == 8·(3+2)+4·100) but different layout
    # -> the identity header catches what the size check cannot
    with pytest.raises(RuntimeError, match="identity mismatch"):
        GridFile(path, 4, 96)
    g.remove()
    g.remove()  # idempotent
    assert not (tmp_path / "g.bin").exists()
