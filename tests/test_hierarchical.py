"""Hierarchical (ICI + ring) all-reduce: two emulated slices on one host.

Each 'slice' is a thread owning half of the 8 virtual CPU devices with its
own Mesh; the cross-slice hop runs over the real native loopback ring. This
is the slice-as-one-peer topology of BASELINE.json's north star."""

import threading
import time
from pathlib import Path

import numpy as np
import pytest

LIB = Path(__file__).resolve().parent.parent / "pccl_tpu" / "native" / "build" / "libpcclt.so"
needs_native = pytest.mark.skipif(not LIB.exists(), reason="native lib not built")


def test_local_mean_shard_map(eight_devices):
    import jax.numpy as jnp

    from pccl_tpu.parallel import mesh as mesh_lib
    from pccl_tpu.parallel.hierarchical import local_mean

    mesh = mesh_lib.make_mesh(eight_devices[:4], axis_names=("dp",), shape=(4,))
    # per-device values 0,1,2,3 stacked along the leading dim → folded mean 1.5
    x = jnp.repeat(jnp.arange(4, dtype=jnp.float32), 8)  # [32] = 4 shards of 8
    out = local_mean(x, mesh, axis="dp")
    assert out.shape == (8,)
    np.testing.assert_allclose(np.asarray(out), np.full(8, 1.5))


def test_identity_without_comm(eight_devices):
    import jax
    import jax.numpy as jnp

    from pccl_tpu.parallel.hierarchical import HierarchicalAllReduce

    tree = {"a": jnp.arange(12, dtype=jnp.float32).reshape(3, 4),
            "b": jnp.ones(5, jnp.bfloat16)}
    h = HierarchicalAllReduce(None, tree)
    out = h.all_reduce(tree)
    np.testing.assert_allclose(np.asarray(out["a"]),
                               np.arange(12, dtype=np.float32).reshape(3, 4))
    assert out["b"].dtype == jnp.bfloat16


@needs_native
def test_two_slices_global_mean(eight_devices):
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from pccl_tpu.comm import Communicator, MasterNode
    from pccl_tpu.parallel import mesh as mesh_lib
    from pccl_tpu.parallel.hierarchical import HierarchicalAllReduce

    master = MasterNode("0.0.0.0", 52300)
    master.run()
    errors = []
    results = {}

    def slice_proc(slice_id):
        try:
            devs = eight_devices[slice_id * 4:(slice_id + 1) * 4]
            mesh = mesh_lib.make_mesh(devs, axis_names=("dp", "tp"), shape=(2, 2))
            # a sharded "gradient": value = slice_id + 1 everywhere
            sharding = NamedSharding(mesh, P("dp", "tp"))
            g = jax.device_put(
                jnp.full((8, 8), float(slice_id + 1), jnp.float32), sharding)
            tree = {"g": g}

            base = 54500 + slice_id * 16
            comm = Communicator("127.0.0.1", master.port, p2p_port=base,
                                ss_port=base + 4, bench_port=base + 8)
            comm.connect()
            deadline = time.time() + 30
            while comm.world_size < 2:
                if time.time() > deadline:
                    raise TimeoutError("world never reached 2")
                if comm.are_peers_pending():
                    comm.update_topology()
                time.sleep(0.01)

            h = HierarchicalAllReduce(comm, tree)
            out = h.all_reduce(tree)
            assert out["g"].sharding.is_equivalent_to(sharding, 2)
            results[slice_id] = np.asarray(out["g"])
            comm.destroy()
        except Exception as e:  # noqa: BLE001
            errors.append((slice_id, e))

    ts = [threading.Thread(target=slice_proc, args=(s,)) for s in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=120)
    master.interrupt()
    master.destroy()
    assert not errors, f"slice failures: {errors}"
    # global mean of 1.0 and 2.0 → 1.5, identical bytes on both slices
    np.testing.assert_array_equal(results[0], results[1])
    np.testing.assert_allclose(results[0], np.full((8, 8), 1.5))


@needs_native
def test_two_slices_quantized_dcn_hop(eight_devices):
    """BASELINE config 4's quantized variant: the cross-slice (DCN) hop runs
    u8 zero-point/scale on the wire while ICI layout/restore stays exact.
    Both slices must end bit-identical (the shared-state hash invariant) and
    within 8-bit range error of the true mean."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from pccl_tpu.comm import (Communicator, DataType, MasterNode,
                               QuantizationAlgorithm)
    from pccl_tpu.parallel import mesh as mesh_lib
    from pccl_tpu.parallel.hierarchical import HierarchicalAllReduce

    master = MasterNode("0.0.0.0", 52600)
    master.run()
    errors = []
    results = {}

    def slice_proc(slice_id):
        try:
            devs = eight_devices[slice_id * 4:(slice_id + 1) * 4]
            mesh = mesh_lib.make_mesh(devs, axis_names=("dp",), shape=(4,))
            sharding = NamedSharding(mesh, P("dp"))
            rng = np.random.default_rng(11)  # SAME base values on both slices
            base = rng.standard_normal(4096).astype(np.float32)
            g = jax.device_put(jnp.asarray(base) + float(slice_id), sharding)

            port = 54700 + slice_id * 16
            comm = Communicator("127.0.0.1", master.port, p2p_port=port,
                                ss_port=port + 4, bench_port=port + 8)
            comm.connect()
            deadline = time.time() + 30
            while comm.world_size < 2:
                if time.time() > deadline:
                    raise TimeoutError("world never reached 2")
                if comm.are_peers_pending():
                    comm.update_topology()
                time.sleep(0.01)

            h = HierarchicalAllReduce(
                comm, {"g": g},
                quantization=QuantizationAlgorithm.ZERO_POINT_SCALE,
                quantized_dtype=DataType.UINT8)
            out = h.all_reduce({"g": g})
            assert out["g"].sharding.is_equivalent_to(sharding, 1)
            results[slice_id] = np.asarray(out["g"])
            comm.destroy()
        except Exception as e:  # noqa: BLE001
            errors.append((slice_id, e))

    ts = [threading.Thread(target=slice_proc, args=(s,)) for s in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=120)
    master.interrupt()
    master.destroy()
    assert not errors, f"slice failures: {errors}"
    # bit-identical across slices (quantized wire bytes forwarded verbatim)
    np.testing.assert_array_equal(results[0], results[1])
    # true mean = base + 0.5; u8-ZPS over the values' range bounds the error
    rng = np.random.default_rng(11)
    want = rng.standard_normal(4096).astype(np.float32) + 0.5
    span = want.max() - want.min() + 1.0  # + slice offsets widen the range
    assert np.abs(results[0] - want).max() < span / 255 * 2
