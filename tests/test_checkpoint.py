"""Checkpoint/resume (pccl_tpu.utils.checkpoint, orbax-backed).

The reference keeps checkpointing an app contract (revision-0 master
bootstrap + periodic dumps); these tests assert the library implementation:
round-trip fidelity, retention, and DiLoCo outer-state resume at the exact
revision.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
pytest.importorskip("orbax.checkpoint")  # the library defers this import
import jax.numpy as jnp  # noqa: E402


def test_checkpointer_roundtrip_and_retention(tmp_path):
    from pccl_tpu.utils.checkpoint import Checkpointer

    ck = Checkpointer(str(tmp_path / "ck"), keep=2)
    tree = {"w": jnp.arange(8, dtype=jnp.float32), "b": jnp.float32(3.5)}
    for step in (1, 2, 3):
        ck.save(step, jax.tree.map(lambda x: x * step, tree))
    assert ck.latest_step() == 3
    out = ck.restore(tree)
    np.testing.assert_array_equal(np.asarray(out["w"]),
                                  np.arange(8, dtype=np.float32) * 3)
    assert float(out["b"]) == 3.5 * 3
    # retention: keep=2 -> step 1 is gone, step 2 restorable
    out2 = ck.restore(tree, step=2)
    assert float(out2["b"]) == 7.0
    # noqa'd broad raises: the purged-step error type varies across orbax
    # versions (FileNotFoundError vs orbax's own CheckpointError)
    with pytest.raises(Exception):  # noqa: B017
        ck.restore(tree, step=1)
    ck.close()


def test_diloco_checkpoint_resume(tmp_path):
    from pccl_tpu.parallel.diloco import Diloco, DilocoConfig
    from pccl_tpu.utils.checkpoint import DilocoCheckpoint

    params = {"w": jnp.zeros((64,), jnp.float32)}
    cfg = DilocoConfig(outer_lr=1.0, outer_momentum=0.9)
    dl = Diloco(None, params, cfg)  # solo: outer_step still applies SGD
    ckpt = DilocoCheckpoint(str(tmp_path / "dck"))
    assert ckpt.maybe_restore(dl) == 0  # fresh start

    p = dl.params()
    for _ in range(3):
        inner = {"w": p["w"] - 0.5}
        p = dl.outer_step(inner)
    ckpt.save(dl)
    want_w = np.asarray(dl.outer_params["w"])
    want_mom = np.asarray(dl._momentum_vec)

    # cold restart: a brand-new driver restores the exact outer revision
    dl2 = Diloco(None, params, cfg)
    resumed = ckpt.maybe_restore(dl2)
    assert resumed == 3 and dl2.step == 3
    np.testing.assert_array_equal(np.asarray(dl2.outer_params["w"]), want_w)
    np.testing.assert_array_equal(np.asarray(dl2._momentum_vec), want_mom)

    # training continues identically from the restored state
    inner = {"w": dl2.params()["w"] - 0.5}
    a = np.asarray(dl2.outer_step(inner)["w"])
    inner_ref = {"w": dl.params()["w"] - 0.5}
    b = np.asarray(dl.outer_step(inner_ref)["w"])
    np.testing.assert_array_equal(a, b)
    ckpt.close()
