"""Drift-injection tests for the pcclt-check linters (tools/pcclt_check).

Each checker must (a) pass on the real tree — the lint lane lands green —
and (b) fail with an actionable message when one specific kind of drift is
injected into a copy/synthetic tree: a renamed ctypes field, a narrowed
width, an orphaned protocol id, an undocumented env var, a stale doc row,
an unchecked thread guard, a dropped lock.  (b) is what keeps the checkers
honest: a linter that cannot fail is documentation, not enforcement.
"""

from __future__ import annotations

import shutil
import textwrap
from pathlib import Path

import pytest

from tools.pcclt_check import abi, env_registry, guards, protocol_ids

ROOT = Path(__file__).resolve().parents[1]
SRC = "pccl_tpu/native/src"


def _msgs(findings):
    return "\n".join(str(f) for f in findings)


# --------------------------------------------------------------- real tree


def test_abi_real_tree_clean():
    assert abi.check(ROOT) == [], _msgs(abi.check(ROOT))


def test_protocol_real_tree_clean():
    assert protocol_ids.check(ROOT) == [], _msgs(protocol_ids.check(ROOT))


def test_env_real_tree_clean():
    assert env_registry.check(ROOT) == [], _msgs(env_registry.check(ROOT))


def test_guards_real_tree_clean():
    assert guards.check(ROOT) == [], _msgs(guards.check(ROOT))


@pytest.mark.slow
def test_tsa_real_tree_clean():
    clang = pytest.importorskip("clang.cindex")
    del clang
    from tools.pcclt_check import thread_safety

    out = thread_safety.check(ROOT)
    assert not isinstance(out, list) or out == [], _msgs(out)


# ----------------------------------------------------------- abi injection


@pytest.fixture
def abi_tree(tmp_path):
    for rel in (abi.HEADER, abi.NATIVE):
        (tmp_path / rel).parent.mkdir(parents=True, exist_ok=True)
        shutil.copy(ROOT / rel, tmp_path / rel)
    return tmp_path


def _edit(root: Path, rel: str, old: str, new: str) -> None:
    p = root / rel
    text = p.read_text()
    assert old in text, f"fixture drift: {old!r} not in {rel}"
    p.write_text(text.replace(old, new, 1))


def test_abi_copy_of_real_tree_passes(abi_tree):
    assert abi.check(abi_tree) == []


def test_abi_catches_renamed_field(abi_tree):
    _edit(abi_tree, abi.NATIVE, '("world_size", ctypes.c_uint32)',
          '("wrld_size", ctypes.c_uint32)')
    out = abi.check(abi_tree)
    assert any("wrld_size" in f.message and "name/order" in f.message
               for f in out), _msgs(out)


def test_abi_catches_width_drift(abi_tree):
    _edit(abi_tree, abi.NATIVE, '("master_port", ctypes.c_uint16)',
          '("master_port", ctypes.c_uint32)')
    out = abi.check(abi_tree)
    assert any("master_port" in f.message and "width drift" in f.message
               for f in out), _msgs(out)


def test_abi_catches_missing_function_mirror(abi_tree):
    _edit(abi_tree, abi.NATIVE,
          "lib.pccltGatherSlot.restype", "lib.pccltGatherSlotX.restype")
    _edit(abi_tree, abi.NATIVE,
          "lib.pccltGatherSlot.argtypes", "lib.pccltGatherSlotX.argtypes")
    out = abi.check(abi_tree)
    # both directions: the bogus declaration and the now-undeclared export
    assert any("pccltGatherSlotX" in f.message for f in out), _msgs(out)
    assert any("pccltGatherSlot " in f.message or
               "pccltGatherSlot but" in f.message for f in out), _msgs(out)


def test_abi_catches_field_count_mismatch(abi_tree):
    _edit(abi_tree, abi.NATIVE, '        ("stall_ms", ctypes.c_uint64),\n', "")
    out = abi.check(abi_tree)
    assert any("EdgeStats" in f.message and "field" in f.message
               for f in out), _msgs(out)


# ------------------------------------------------------ protocol injection


@pytest.fixture
def proto_tree(tmp_path):
    for rel in (f"{SRC}/protocol.hpp", f"{SRC}/protocol.cpp",
                f"{SRC}/client.cpp", f"{SRC}/master.cpp",
                f"{SRC}/master_state.cpp", f"{SRC}/sockets.hpp",
                f"{SRC}/sockets.cpp", f"{SRC}/benchmark.cpp"):
        (tmp_path / rel).parent.mkdir(parents=True, exist_ok=True)
        shutil.copy(ROOT / rel, tmp_path / rel)
    return tmp_path


def test_protocol_copy_of_real_tree_passes(proto_tree):
    assert protocol_ids.check(proto_tree) == []


def test_protocol_catches_orphaned_id(proto_tree):
    _edit(proto_tree, f"{SRC}/protocol.hpp",
          "kC2MSessionResume = 0x100C,",
          "kC2MSessionResume = 0x100C,\n    kC2MBogusNewThing = 0x10FF,")
    out = protocol_ids.check(proto_tree)
    assert any("kC2MBogusNewThing" in f.message and "never sent" in f.message
               for f in out), _msgs(out)
    assert any("kC2MBogusNewThing" in f.message and "dispatch arm" in f.message
               for f in out), _msgs(out)


def test_protocol_catches_duplicate_id_value(proto_tree):
    _edit(proto_tree, f"{SRC}/protocol.hpp",
          "kM2CSessionResumeAck = 0x200E,", "kM2CSessionResumeAck = 0x200C,")
    out = protocol_ids.check(proto_tree)
    assert any("reuses packet id 0x200C" in f.message for f in out), _msgs(out)


def test_protocol_catches_missing_dispatch_arm(proto_tree):
    _edit(proto_tree, f"{SRC}/master.cpp",
          "case PacketType::kC2MOptimizeTopology:",
          "/* dispatch arm dropped by fixture */ default:")
    out = protocol_ids.check(proto_tree)
    assert any("kC2MOptimizeTopology" in f.message and "dispatch arm" in f.message
               for f in out), _msgs(out)


def test_protocol_catches_orphaned_frame_kind(proto_tree):
    _edit(proto_tree, f"{SRC}/sockets.hpp",
          "kChunkHdr = 12,", "kChunkHdr = 12,\n        kBogusKind = 13,")
    out = protocol_ids.check(proto_tree)
    assert any("kBogusKind" in f.message and "rx handler arm" in f.message
               for f in out), _msgs(out)
    assert any("kBogusKind" in f.message and "tx_loop" in f.message
               for f in out), _msgs(out)


def test_protocol_catches_duplicate_frame_kind_value(proto_tree):
    _edit(proto_tree, f"{SRC}/sockets.hpp",
          "kChunkHdr = 12,", "kChunkHdr = 11,")
    out = protocol_ids.check(proto_tree)
    assert any("reuses wire value 11" in f.message for f in out), _msgs(out)


def test_protocol_catches_lost_kdata_marker(proto_tree):
    _edit(proto_tree, f"{SRC}/sockets.cpp",
          "// kData — sink fast path", "// data path")
    out = protocol_ids.check(proto_tree)
    assert any("sink fast path" in f.message for f in out), _msgs(out)


def test_protocol_catches_missing_decoder(proto_tree):
    _edit(proto_tree, f"{SRC}/protocol.cpp",
          "std::optional<CollectiveInit> CollectiveInit::decode",
          "std::optional<CollectiveInit> CollectiveInit::decode_renamed")
    out = protocol_ids.check(proto_tree)
    assert any("CollectiveInit::decode" in f.message for f in out), _msgs(out)


# ----------------------------------------------------------- env injection


@pytest.fixture
def env_tree(tmp_path):
    src = tmp_path / SRC
    src.mkdir(parents=True)
    inc = tmp_path / "pccl_tpu/native/include"
    inc.mkdir(parents=True)
    # concatenation keeps these fixture strings invisible to the checker's
    # own scan of tests/*.py (it would otherwise read them as real env reads)
    (src / "thing.cpp").write_text(
        'const char *a = get' + 'env("PCCLT_DOCUMENTED");\n'
        '#define PCCLT_SOME_MACRO 1\n')
    (tmp_path / "docs").mkdir()
    (tmp_path / env_registry.DOC_TABLE).write_text(textwrap.dedent("""\
        | Var | Default | Meaning |
        |---|---|---|
        | `PCCLT_DOCUMENTED` | `1` | a documented knob |
        """))
    (tmp_path / "README.md").write_text("mentions `PCCLT_SOME_MACRO` only\n")
    return tmp_path


def test_env_synthetic_tree_passes(env_tree):
    assert env_registry.check(env_tree) == []


def test_env_catches_undocumented_var(env_tree):
    p = env_tree / SRC / "thing.cpp"
    p.write_text(p.read_text() +
                 'const char *b = get' + 'env("PCCLT_SECRET_KNOB");\n')
    out = env_registry.check(env_tree)
    assert any("PCCLT_SECRET_KNOB" in f.message and "document it" in f.message
               for f in out), _msgs(out)


def test_env_catches_stale_doc_row(env_tree):
    p = env_tree / env_registry.DOC_TABLE
    p.write_text(p.read_text() +
                 "| `PCCLT_REMOVED_KNOB` | `0` | gone from the code |\n")
    out = env_registry.check(env_tree)
    assert any("PCCLT_REMOVED_KNOB" in f.message and "stale" in f.message
               for f in out), _msgs(out)


def test_env_sees_helper_routed_reads(env_tree):
    # a PCCLT_* name flowing through an env-reader helper (native_bench's
    # _port pattern) must count as a read — undocumented => finding
    (env_tree / SRC.replace("native/src", "") ).mkdir(exist_ok=True)
    helper = env_tree / "pccl_tpu" / "helper_mod.py"
    helper.write_text(
        "import os\n"
        "def _port(env, dflt):\n"
        "    return int(os.environ.get(env, str(dflt)))\n"
        "def leg(port_env='PCCLT_HELPER_KNOB', port=1):\n"
        "    return _port(port_env, port)\n"
        "leg(port_env='PCCLT_HELPER_KNOB_WAN')\n")
    out = env_registry.check(env_tree)
    assert any("PCCLT_HELPER_KNOB" in f.message and "document it" in f.message
               for f in out), _msgs(out)
    # one family row covers the base name AND the suffixed variant
    table = env_tree / env_registry.DOC_TABLE
    table.write_text(table.read_text() +
                     "| `PCCLT_HELPER_KNOB` | `1` | helper-routed knob family |\n")
    assert env_registry.check(env_tree) == [], _msgs(env_registry.check(env_tree))


def test_env_catches_misspelled_doc_mention(env_tree):
    p = env_tree / "README.md"
    p.write_text(p.read_text() + "set `PCCLT_DOCUMENTD` to tune it\n")
    out = env_registry.check(env_tree)
    assert any("PCCLT_DOCUMENTD" in f.message for f in out), _msgs(out)


# -------------------------------------------------------- guards injection


@pytest.fixture
def guard_tree(tmp_path):
    src = tmp_path / SRC
    src.mkdir(parents=True)
    (src / "machine.hpp").write_text(textwrap.dedent("""\
        #pragma once
        // single-threaded by design: one loop thread drives the machine
        class Machine {
            ThreadGuard guard_;
        };
        """))
    (src / "machine.cpp").write_text(
        "void Machine::loop() { PCCLT_THREAD_GUARD(guard_); }\n")
    return tmp_path


def test_guards_synthetic_tree_passes(guard_tree):
    assert guards.check(guard_tree) == []


def test_guards_catches_marker_without_guard(guard_tree):
    (guard_tree / SRC / "machine.hpp").write_text(textwrap.dedent("""\
        #pragma once
        // single-threaded by design: one loop thread drives the machine
        class Machine {
            int x_;
        };
        """))
    (guard_tree / SRC / "machine.cpp").write_text("void f() {}\n")
    out = guards.check(guard_tree)
    assert any("declares no pcclt::ThreadGuard" in f.message
               for f in out), _msgs(out)


def test_guards_catches_unchecked_guard(guard_tree):
    (guard_tree / SRC / "machine.cpp").write_text("void Machine::loop() {}\n")
    out = guards.check(guard_tree)
    assert any("nobody checks" in f.message and "guard_" in f.message
               for f in out), _msgs(out)


def test_guards_catches_ambiguous_guard_name(guard_tree):
    # two classes sharing a guard member name: one class's call must not
    # satisfy the other's missing check — the checker demands unique names
    (guard_tree / SRC / "other.hpp").write_text(
        "#pragma once\nclass Other {\n    ThreadGuard guard_;\n};\n")
    out = guards.check(guard_tree)
    assert any("multiple" in f.message and "guard_" in f.message
               for f in out), _msgs(out)


def test_guards_catches_stale_call(guard_tree):
    (guard_tree / SRC / "machine.cpp").write_text(
        "void Machine::loop() { PCCLT_THREAD_GUARD(guard_); "
        "PCCLT_THREAD_GUARD(old_guard_); }\n")
    out = guards.check(guard_tree)
    assert any("old_guard_" in f.message and "no declared" in f.message
               for f in out), _msgs(out)


# ----------------------------------------------------------- tsa injection


@pytest.fixture
def tsa_tree(tmp_path):
    pytest.importorskip("clang.cindex")
    src = tmp_path / SRC
    src.mkdir(parents=True)
    (tmp_path / "pccl_tpu/native/include").mkdir(parents=True)
    shutil.copy(ROOT / SRC / "annotations.hpp", src / "annotations.hpp")
    (src / "tiny.cpp").write_text(textwrap.dedent("""\
        #include "annotations.hpp"
        struct Counter {
            pcclt::Mutex mu;
            int n PCCLT_GUARDED_BY(mu) = 0;
            void bump() {
                pcclt::MutexLock lk(mu);
                ++n;
            }
        };
        int main() { Counter c; c.bump(); return 0; }
        """))
    return tmp_path


def test_tsa_clean_tu_passes(tsa_tree):
    from tools.pcclt_check import thread_safety

    out = thread_safety.check(tsa_tree)
    assert out == [], _msgs(out)


def test_tsa_catches_unlocked_write(tsa_tree):
    from tools.pcclt_check import thread_safety

    _edit(tsa_tree, f"{SRC}/tiny.cpp", "        pcclt::MutexLock lk(mu);\n", "")
    out = thread_safety.check(tsa_tree)
    assert any("requires holding mutex 'mu'" in f.message
               for f in out), _msgs(out)
