"""Telemetry test peer (subprocess worker).

One peer of a wire_topology-emulated loopback world: applies its per-rank
PCCLT_WIRE_*_MAP env BEFORE touching the native layer, runs one fp32 ring
all-reduce with the flight recorder enabled, and prints a single JSON line
with its Communicator.stats() snapshot. Rank 0 additionally exports a
MERGED Chrome trace (Python profiler sections + native recorder events) to
--trace-out. The orchestrating test (test_telemetry.py) asserts per-edge
byte conservation across the collected stats and that the merged trace
parses as a valid perfetto-loadable trace.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--master-port", type=int, required=True)
    ap.add_argument("--rank", type=int, required=True)
    ap.add_argument("--world", type=int, required=True)
    ap.add_argument("--port-base", type=int, required=True)
    ap.add_argument("--count", type=int, default=1 << 18)
    ap.add_argument("--env", default="{}",
                    help="JSON env dict applied before the native load "
                         "(per-rank wire_topology maps)")
    ap.add_argument("--trace-out", default=None,
                    help="rank 0: write the merged Python+native Chrome "
                         "trace here")
    args = ap.parse_args()

    os.environ.update(json.loads(args.env))

    import numpy as np

    from pccl_tpu.comm import Communicator, ReduceOp, trace_enable, trace_events
    from pccl_tpu.comm.native_bench import _rank_ports
    from pccl_tpu.utils.profiler import Profiler

    trace_enable(True)
    p2p, ss, bench = _rank_ports(args.port_base, args.rank)
    comm = Communicator("127.0.0.1", args.master_port,
                        p2p_port=p2p, ss_port=ss, bench_port=bench)
    comm.connect()
    deadline = time.time() + 60
    while comm.world_size < args.world:
        if time.time() > deadline:
            print(json.dumps({"rank": args.rank, "error": "world timeout"}),
                  flush=True)
            return 2
        if comm.are_peers_pending():
            comm.update_topology()
        time.sleep(0.02)

    prof = Profiler()
    x = np.full(args.count, float(args.rank + 1), dtype=np.float32)
    t0 = time.perf_counter()
    with prof.section("py/all_reduce"):
        comm.all_reduce(x, op=ReduceOp.SUM, tag=0)
    elapsed = time.perf_counter() - t0
    expect = args.world * (args.world + 1) / 2
    if float(x[0]) != expect or float(x[-1]) != expect:
        print(json.dumps({"rank": args.rank,
                          "error": f"bad result {x[0]} != {expect}"}),
              flush=True)
        return 3
    stats = comm.stats()
    if args.trace_out:
        prof.export_chrome_trace(args.trace_out, native_events=trace_events())
    print(json.dumps({"rank": args.rank, "stats": stats,
                      "elapsed_s": elapsed}), flush=True)
    comm.destroy()
    return 0


if __name__ == "__main__":
    sys.exit(main())
