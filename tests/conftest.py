"""Test configuration: force an 8-virtual-device CPU platform BEFORE jax init.

Multi-chip sharding is validated on a virtual CPU mesh (no multi-chip TPU
hardware in CI); the real-chip path is exercised by bench.py.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
if os.environ.get("PCCLT_TEST_TPU") != "1":
    os.environ["JAX_PLATFORMS"] = "cpu"  # tests run on a virtual CPU mesh, always
    # jax may already be imported by a pytest plugin; config.update still works
    # as long as no backend has been initialized yet.
    import jax

    jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def eight_devices():
    import jax

    devs = jax.devices()
    assert len(devs) >= 8, f"expected >=8 virtual devices, got {len(devs)}"
    return devs[:8]


# single in-process port allocator: every test file draws disjoint ranges
# from here instead of hand-picking bases that can silently collide.
# The base sits BELOW the kernel ephemeral range (32768-60999): a listener
# in that range can lose its port to any stray outbound socket while down
# (e.g. the master-restart soak), making binds flaky under suite load.
_PORT_COUNTER = [20000]


def alloc_ports(span: int = 64) -> int:
    p = _PORT_COUNTER[0]
    _PORT_COUNTER[0] += span
    return p
