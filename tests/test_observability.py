"""Fleet observability plane (docs/09): live telemetry digests to the
master, the Prometheus /metrics + JSON /health endpoint, cross-peer trace
correlation, and telemetry-driven straggler flagging.

The acceptance scenarios from the three tiers:
  * conservation through aggregation — a LIVE scrape of the master's
    /metrics during a netem 4-peer run must report per-edge byte totals
    that agree exactly with the peers' own stats() counters;
  * a master SIGKILL + journal restart preserves /health continuity (the
    epoch survives and bumps, peers reappear via resumed sessions);
  * a netem-degraded edge (fast bandwidth probes, throttled data plane)
    raises the straggler flag in /health within a push interval, without
    stopping the run;
  * tools/trace_merge aligns per-peer Chrome traces on (epoch, seq).

Multi-peer behavior runs real processes, never mocks (the repo's
stress-test discipline)."""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.request
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
LIB = REPO / "pccl_tpu" / "native" / "build" / "libpcclt.so"
pytestmark = pytest.mark.skipif(not LIB.exists(), reason="native lib not built")

from conftest import alloc_ports  # noqa: E402


def _scrape(port: int, path: str = "/metrics", timeout: float = 5.0) -> str:
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}",
                                timeout=timeout) as r:
        text = r.read().decode()
    if path.startswith("/metrics"):
        # every scrape ANY test takes must be strictly valid exposition
        # text — a real scraper rejects the whole page on one bad line
        from pccl_tpu.comm import promlint
        promlint.assert_valid(text, context=f"GET {path}")
    return text


def _prom_samples(text: str, name: str) -> dict:
    """{frozenset(label items): float value} for one metric family."""
    out = {}
    for line in text.splitlines():
        if not line.startswith(name + "{"):
            continue
        labels, _, value = line[len(name) + 1:].partition("} ")
        items = []
        for part in labels.split('",'):
            k, _, v = part.partition('="')
            items.append((k, v.rstrip('"')))
        out[frozenset(items)] = float(value)
    return out


# ---------------------------------------------------------------- tier 3


def test_trace_merge_alignment(tmp_path):
    """Two synthetic peer traces whose clocks disagree by 5 seconds merge
    onto one timeline: spans sharing (epoch, seq) end at the same merged
    timestamp, pids stay distinct, process names keep their peer prefix."""
    from tools.trace_merge import merge_files

    def trace(base_us, peer):
        evs = [{"ph": "M", "name": "process_name", "pid": 1,
                "args": {"name": "pcclt native"}}]
        for seq in (11, 12, 13):
            t = base_us + seq * 1000.0
            evs.append({"name": "allreduce", "cat": "collective", "ph": "X",
                        "pid": 1, "tid": 7, "ts": t, "dur": 400.0 + peer,
                        "args": {"seq": seq, "epoch": 2}})
        # an unanchored python-side section rides along untouched
        evs.append({"name": "py/step", "ph": "X", "pid": 0, "tid": 1,
                    "ts": base_us, "dur": 5000.0, "args": {}})
        return {"traceEvents": evs}

    a, b = tmp_path / "a.json", tmp_path / "b.json"
    a.write_text(json.dumps(trace(1_000_000.0, 0)))
    b.write_text(json.dumps(trace(6_000_000.0, 1)))  # clock 5 s ahead
    merged = merge_files([a, b])
    meta = merged["metadata"]
    assert meta["shared_anchors"]["b"] == 3
    assert abs(meta["offsets_us"]["b"] + 5_000_000.0) < 2.0
    ends = {}
    for e in merged["traceEvents"]:
        if e.get("name") == "allreduce":
            key = (e["args"]["epoch"], e["args"]["seq"], e["pid"])
            ends[key] = e["ts"] + e["dur"]
    # per (epoch, seq): both peers' spans end within the dur skew we built
    for seq in (11, 12, 13):
        per_seq = [v for (ep, s, _), v in ends.items() if s == seq]
        assert len(per_seq) == 2
        assert abs(per_seq[0] - per_seq[1]) <= 1.5
    pids = {e.get("pid") for e in merged["traceEvents"] if "pid" in e}
    assert len(pids) == 4  # (2 peers) x (python pid 0 + native pid 1)
    names = [e["args"]["name"] for e in merged["traceEvents"]
             if e.get("name") == "process_name"]
    assert any(n.startswith("a: ") for n in names)
    assert any(n.startswith("b: ") for n in names)


def test_trace_merge_cli_rejects_unanchored(tmp_path):
    """Merging traces that share no collective anchor must fail loudly
    (exit 1), not produce a silently misaligned artifact."""
    a, b = tmp_path / "a.json", tmp_path / "b.json"
    a.write_text(json.dumps({"traceEvents": [
        {"name": "allreduce", "ph": "X", "pid": 1, "tid": 1, "ts": 1.0,
         "dur": 2.0, "args": {"seq": 1}}]}))
    b.write_text(json.dumps({"traceEvents": [
        {"name": "py/step", "ph": "X", "pid": 0, "tid": 1, "ts": 9.0,
         "dur": 2.0, "args": {}}]}))
    r = subprocess.run(
        [sys.executable, "-m", "tools.trace_merge", str(a), str(b),
         "-o", str(tmp_path / "out.json")],
        cwd=str(REPO), capture_output=True, text=True)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "no shared collective anchors" in r.stderr
    r = subprocess.run(
        [sys.executable, "-m", "tools.trace_merge", str(a), str(b),
         "--allow-unanchored", "-o", str(tmp_path / "out.json")],
        cwd=str(REPO), capture_output=True, text=True)
    assert r.returncode == 0, r.stdout + r.stderr
    assert (tmp_path / "out.json").exists()


def _stage_span(peer_pid, seq, stage, ts, dur, stall_ns, edge, epoch=1):
    return {"name": "rs_stage", "cat": "collective", "ph": "X",
            "pid": peer_pid, "tid": 7, "ts": ts, "dur": dur,
            "args": {"stage": stage, "seq": seq, "stall_ns": stall_ns,
                     "detail": edge, "epoch": epoch}}


def _synth_peer_trace(label, seqs, stall_edge=None, stall_us=0.0,
                      setup_us=500.0, epoch=1):
    """One synthetic peer timeline: commence_wait -> op_setup -> two
    stages -> op span, optionally with a dominant stall on `stall_edge`."""
    evs = []
    for seq in seqs:
        t = seq * 1_000_000.0
        cw, setup = 300.0, setup_us
        st = [20_000.0 + stall_us / 2, 20_000.0 + stall_us / 2]
        evs.append({"name": "commence_wait", "ph": "X", "pid": 1, "tid": 7,
                    "ts": t, "dur": cw,
                    "args": {"tag": 0, "seq": seq, "epoch": epoch}})
        evs.append({"name": "op_setup", "ph": "X", "pid": 1, "tid": 7,
                    "ts": t + cw, "dur": setup,
                    "args": {"seq": seq, "epoch": epoch}})
        ring0 = t + cw + setup + 10.0
        evs.append(_stage_span(1, seq, 0, ring0, st[0],
                               (stall_us / 2) * 1e3 if stall_edge else 0,
                               stall_edge or "10.0.0.1:1", epoch))
        evs.append(_stage_span(1, seq, 1, ring0 + st[0], st[1],
                               (stall_us / 2) * 1e3 if stall_edge else 0,
                               stall_edge or "10.0.0.1:1", epoch))
        evs.append({"name": "allreduce", "cat": "collective", "ph": "X",
                    "pid": 1, "tid": 7, "ts": ring0,
                    "dur": st[0] + st[1] + 5.0,
                    "args": {"seq": seq, "bytes": 1 << 20, "epoch": epoch}})
    return {"traceEvents": evs}


def test_trace_critic_attribution_unit(tmp_path):
    """tools/trace_critic on synthetic two-peer traces: attribution covers
    >= 95% of each collective, the peer with a dominant single-edge stall
    makes its ops stall-straggler verdicts naming that edge, and the edge
    tops the run-level critical-path ranking."""
    from tools.trace_critic import analyze_files

    bad_edge = "10.0.0.9:48502"
    a = tmp_path / "a.json"
    b = tmp_path / "b.json"
    a.write_text(json.dumps(_synth_peer_trace("a", (11, 12, 13))))
    # peer b: 80 ms of stall per op, all witnessed on one inbound edge —
    # b is the binding peer (longer ops) and the verdict must name it
    b.write_text(json.dumps(_synth_peer_trace("b", (11, 12, 13),
                                              stall_edge=bad_edge,
                                              stall_us=80_000.0)))
    report = analyze_files([a, b])
    agg = report["aggregate"]
    assert agg["ops"] == 3, agg
    assert agg["mean_coverage"] >= 0.95, agg
    assert agg["critical_edge"] == bad_edge, agg
    assert agg["critical_witness"] == "b", agg
    assert agg["verdicts"].get("stall-straggler") == 3, agg
    for c in report["collectives"]:
        assert c["binding_peer"] == "b"
        assert c["critical_edge"] == bad_edge
        assert c["coverage"] >= 0.95
        assert c["fracs"]["stall"] > 0.5

    # CLI: gate passes at 0.95, report lands on disk
    out = tmp_path / "report.json"
    r = subprocess.run(
        [sys.executable, "-m", "tools.trace_critic", str(a), str(b),
         "-o", str(out), "--min-coverage", "0.95"],
        cwd=str(REPO), capture_output=True, text=True)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "critical path" in r.stdout and bad_edge in r.stdout
    assert json.loads(out.read_text())["aggregate"]["critical_edge"] == bad_edge

    # coverage gate: op spans with NO stage decomposition must fail it
    bare = tmp_path / "bare.json"
    bare.write_text(json.dumps({"traceEvents": [
        {"name": "allreduce", "ph": "X", "pid": 1, "tid": 1,
         "ts": 1000.0, "dur": 90_000.0, "args": {"seq": 1, "epoch": 1}}]}))
    r = subprocess.run(
        [sys.executable, "-m", "tools.trace_critic", str(bare),
         "--min-coverage", "0.95"],
        cwd=str(REPO), capture_output=True, text=True)
    assert r.returncode == 2, r.stdout + r.stderr


def test_trace_critic_watchdog_verdict_override(tmp_path):
    """In a coupled ring every peer stalls comparably; a watchdog
    edge_confirm event must therefore outrank the stall ranking and name
    the CONFIRMed outbound edge as the critical path."""
    from tools.trace_critic import analyze_files

    confirmed = "10.0.0.7:48502"
    doc = _synth_peer_trace("a", (21, 22), stall_edge="10.0.0.1:1",
                            stall_us=50_000.0)
    doc["traceEvents"].append({
        "name": "edge_confirm", "cat": "watchdog", "ph": "i", "pid": 1,
        "tid": 7, "ts": 21_050_000.0, "s": "t",
        "args": {"bytes": 1 << 20, "seq": 21, "detail": confirmed,
                 "epoch": 1}})
    p = tmp_path / "a.json"
    p.write_text(json.dumps(doc))
    report = analyze_files([p])
    agg = report["aggregate"]
    assert agg["critical_edge"] == confirmed, agg
    assert agg["critical_witness"] == "watchdog", agg
    op21 = next(c for c in report["collectives"] if c["seq"] == 21)
    assert op21["verdict"] == "stall-straggler"
    assert op21["critical_edge"] == confirmed


def test_stats_exposes_digest_and_ring_drop_counters():
    """stats() carries the new observability counters, and the trace dump
    header (pcclt_trace_meta) reports ring accounting."""
    from pccl_tpu.comm import (Communicator, MasterNode, trace_clear,
                               trace_enable, trace_events)

    master = MasterNode("0.0.0.0", alloc_ports())
    master.run()
    try:
        comm = Communicator("127.0.0.1", master.port,
                            p2p_port=alloc_ports(span=64))
        comm.connect()
        s = comm.stats()["counters"]
        # push cadence not configured in this process: counter present, 0
        assert s["telemetry_digests"] == 0
        assert s["trace_ring_dropped"] == 0
        # ring accounting rides stats() too (satellite: saturation must be
        # visible without a post-hoc artifact)
        assert s["trace_ring_capacity"] == 1 << 16
        assert s["trace_ring_pushed"] >= 0
        trace_enable(True)
        evs = comm.trace_events()
        meta = [e for e in evs if e.get("name") == "pcclt_trace_meta"]
        assert meta, "trace dump header missing"
        args = meta[0]["args"]
        assert {"captured", "pushed", "dropped", "ring_cap",
                "epoch"} <= set(args)
        assert args["dropped"] == 0
        assert args["epoch"] >= 1  # stamped at welcome
        # health is served through the C API even with HTTP disabled
        h = master.health()
        assert h["epoch"] == 1
        assert master.metrics_port == 0
        comm.destroy()
        trace_enable(False)
        trace_clear()
    finally:
        master.interrupt()
        master.destroy()


# ------------------------------------------------- live multi-process tiers


class _ObsPeer:
    def __init__(self, master_port, rank, world, port_base, envs, **kw):
        cmd = [sys.executable, str(REPO / "tests" / "obs_peer.py"),
               "--master-port", str(master_port), "--rank", str(rank),
               "--world", str(world), "--port-base", str(port_base),
               "--env", json.dumps(envs)]
        for k, v in kw.items():
            flag = f"--{k.replace('_', '-')}"
            if v is True:
                cmd.append(flag)
            elif v is not False and v is not None:
                cmd += [flag, str(v)]
        self.proc = subprocess.Popen(cmd, stdin=subprocess.PIPE,
                                     stdout=subprocess.PIPE,
                                     stderr=subprocess.STDOUT, text=True)

    def wait_stats(self, timeout=120):
        """Read lines until the stats JSON appears (peer then holds)."""
        deadline = time.time() + timeout
        line = ""
        while time.time() < deadline:
            line = self.proc.stdout.readline()
            if not line:
                raise AssertionError("peer exited before printing stats")
            line = line.strip()
            if line.startswith("{"):
                d = json.loads(line)
                assert "error" not in d, d
                return d
        raise AssertionError(f"no stats line within {timeout}s: {line}")

    def release(self):
        try:
            self.proc.stdin.write("go\n")
            self.proc.stdin.flush()
        except (BrokenPipeError, OSError):
            pass

    def join(self, timeout=60):
        return self.proc.wait(timeout=timeout)

    def kill(self):
        if self.proc.poll() is None:
            self.proc.kill()
        self.proc.wait(timeout=10)


def _artifact_dir():
    d = os.environ.get("OBS_ARTIFACT_DIR")
    return Path(d) if d else None


def test_metrics_conservation_live_scrape(tmp_path):
    """The tier-2/3 acceptance: a 4-peer netem world with digests on; a
    LIVE /metrics scrape must agree exactly with every peer's stats()
    per-edge byte totals, and the per-peer traces merge into one fleet
    timeline on (epoch, seq)."""
    from pccl_tpu.comm import MasterNode
    from pccl_tpu.comm.native_bench import _rank_ports, wire_topology
    from tools.trace_merge import merge_files

    world, count, push_ms = 4, 1 << 18, 150
    port_base = alloc_ports(span=2300)
    os.environ["PCCLT_MASTER_METRICS_PORT"] = "0"
    master = MasterNode("0.0.0.0", alloc_ports())
    try:
        master.run()
        mp = master.metrics_port
        assert mp > 0
        peers = []
        traces = [tmp_path / f"peer{r}.json" for r in range(world)]
        with wire_topology(world, port_base, mbps=4000.0) as envs:
            for r in range(world):
                peers.append(_ObsPeer(master.port, r, world, port_base,
                                      envs[r], push_ms=push_ms, count=count,
                                      iters=3, hold=True,
                                      trace_out=str(traces[r])))
            try:
                stats = {}
                for r, p in enumerate(peers):
                    stats[r] = p.wait_stats()["stats"]

                # peers alive and holding: scrape LIVE
                nbytes = count * 4
                expected_per_peer = 3 * 2 * (world - 1) * nbytes // world
                deadline = time.time() + 30
                while True:
                    prom = _scrape(mp)
                    tx = _prom_samples(prom, "pcclt_edge_tx_bytes_total")
                    total = sum(tx.values())
                    if total == world * expected_per_peer:
                        break
                    assert time.time() < deadline, \
                        f"scrape never converged: {total} != " \
                        f"{world * expected_per_peer}\n{prom[:2000]}"
                    time.sleep(0.2)

                # exact per-edge agreement: every peer edge appears in the
                # scrape with the same cumulative byte counters
                rx = _prom_samples(prom, "pcclt_edge_rx_bytes_total")
                endpoint_of = {r: f"127.0.0.1:{_rank_ports(port_base, r)[0]}"
                               for r in range(world)}
                for r in range(world):
                    for ep, e in stats[r]["edges"].items():
                        match = [v for k, v in tx.items()
                                 if ("to", ep) in k]
                        assert e["tx_bytes"] in match, (r, ep, e, tx)
                        match_rx = [v for k, v in rx.items()
                                    if ("to", ep) in k]
                        assert e["rx_bytes"] in match_rx
                # all four peers report in /health, all up
                health = json.loads(_scrape(mp, "/health"))
                ups = [p for p in health["peers"] if p["up"]]
                assert len(ups) == world, health
                assert health["telemetry_digests"] >= world
                assert all(p["last_seq"] >= 3 for p in ups), health
                if (d := _artifact_dir()):
                    (d / "fleet_health.json").write_text(json.dumps(health))
                    (d / "metrics.prom").write_text(prom)
            finally:
                for p in peers:
                    p.release()
            for i, p in enumerate(peers):
                assert p.join() == 0, f"peer {i} failed"
    finally:
        os.environ.pop("PCCLT_MASTER_METRICS_PORT", None)
        master.interrupt()
        master.destroy()

    # tier-3 correlation: the four dumps merge into ONE aligned timeline
    merged = merge_files(traces)
    meta = merged["metadata"]
    assert all(n >= 3 for n in meta["shared_anchors"].values()), meta
    by_key = {}
    for e in merged["traceEvents"]:
        if e.get("name") == "allreduce":
            args = e.get("args", {})
            by_key.setdefault((args.get("epoch"), args["seq"]),
                              []).append(e["ts"] + e["dur"])
    full = {k: v for k, v in by_key.items() if len(v) == world}
    assert full, f"no (epoch, seq) shared by all peers: {by_key}"
    for key, ends in full.items():
        # collectives complete near-simultaneously: after alignment all
        # peers' op ends for one (epoch, seq) sit within a second
        assert max(ends) - min(ends) < 1e6, (key, ends)
    if (d := _artifact_dir()):
        (d / "fleet_trace.json").write_text(json.dumps(merged))


def test_phase_histograms_and_ring_gauges_on_scrape():
    """Critical-path attribution on /metrics: a live 2-peer world's digests
    must surface per-(peer, phase) latency HISTOGRAM series (cumulative le
    buckets closing with +Inf, _sum/_count, p50/p99 summary gauges),
    per-edge stage/stall histograms, and the flight-recorder ring gauges —
    and a scrape with histograms stays fast."""
    from pccl_tpu.comm import MasterNode

    from pccl_tpu.comm.native_bench import wire_topology

    world, push_ms, iters = 2, 120, 3
    port_base = alloc_ports(span=2300)
    os.environ["PCCLT_MASTER_METRICS_PORT"] = "0"
    master = MasterNode("0.0.0.0", alloc_ports())
    try:
        master.run()
        mp = master.metrics_port
        peers = []
        with wire_topology(world, port_base, mbps=4000.0) as envs:
            for r in range(world):
                peers.append(_ObsPeer(master.port, r, world, port_base,
                                      envs[r], push_ms=push_ms,
                                      count=1 << 18, iters=iters, hold=True))
            try:
                for p in peers:
                    p.wait_stats()
                # histogram series converge once a digest after the last
                # op lands: phase="op" count must equal the op count
                deadline = time.time() + 30
                prom = ""
                while time.time() < deadline:
                    t0 = time.time()
                    prom = _scrape(mp)
                    scrape_s = time.time() - t0
                    counts = _prom_samples(prom,
                                           "pcclt_phase_latency_seconds_count")
                    op_counts = [v for k, v in counts.items()
                                 if ("phase", "op") in k]
                    if len(op_counts) == world and \
                            all(v == iters for v in op_counts):
                        break
                    time.sleep(0.2)
                assert op_counts and all(v == iters for v in op_counts), \
                    prom[:3000]
                # a loopback scrape with full histogram series stays cheap
                # (the N=1000-edge bound lives in the native selftest)
                assert scrape_s < 5.0, scrape_s

                # cumulative le buckets: monotone, closed by +Inf == _count
                buckets = _prom_samples(prom,
                                        "pcclt_phase_latency_seconds_bucket")
                for k, total in counts.items():
                    series = {dict(k2).get("le"): v for k2, v in
                              buckets.items() if k <= k2 or
                              {i for i in k2 if i[0] != "le"} == set(k)}
                    assert series.get("+Inf") == total, (k, series, total)
                    finite = sorted((float(le), v) for le, v in series.items()
                                    if le and le != "+Inf")
                    vals = [v for _, v in finite]
                    assert vals == sorted(vals), series
                # every attribution phase reported something: the op ran
                # through commence/setup/stage/stall at least
                phases = {dict(k).get("phase") for k in counts}
                assert {"op", "commence_wait", "op_setup",
                        "stage_wire"} <= phases, phases
                # quantile summary gauges ride along
                p99 = _prom_samples(prom, "pcclt_phase_latency_p99_seconds")
                assert any(("phase", "op") in k and v > 0
                           for k, v in p99.items()), p99
                # per-edge histograms name the hop
                est = _prom_samples(prom,
                                    "pcclt_edge_stage_latency_seconds_count")
                assert est and all(v >= 1 for v in est.values()), prom[:2000]
                # ring gauges (satellite): pushed/capacity per peer + the
                # master's own ring
                cap = _prom_samples(prom, "pcclt_peer_trace_ring_capacity")
                assert cap and all(v == (1 << 16) for v in cap.values()), cap
                pushed = _prom_samples(prom, "pcclt_peer_trace_ring_pushed")
                assert pushed and all(v > 0 for v in pushed.values()), pushed
                assert "pcclt_master_trace_ring_capacity " in prom
            finally:
                for p in peers:
                    p.release()
            for i, p in enumerate(peers):
                assert p.join() == 0, f"peer {i} failed"
    finally:
        os.environ.pop("PCCLT_MASTER_METRICS_PORT", None)
        master.interrupt()
        master.destroy()


def test_incident_bundle_on_watchdog_confirm(tmp_path):
    """The ISSUE-11 acceptance e2e: a scripted degrade on one ring edge of
    a 4-peer netem world escalates through the watchdog to CONFIRMED; the
    victim's digest carries wd_state=2 and the master fires ONE
    kM2CIncidentDump broadcast — every live peer writes its trace ring +
    stats snapshot under the shared incident id, the master writes the
    manifest with a fleet-health snapshot, /health lists the incident, and
    tools/trace_critic attributes >= 95% of each collective's wall time
    and names the degraded edge as the critical path."""
    import shutil

    from pccl_tpu.comm import MasterNode
    from pccl_tpu.comm.native_bench import wire_topology
    from tools.trace_critic import analyze_files

    world, count, steps, fault_at = 4, 1 << 19, 9, 3
    fault = "degrade@t=0s:10mbit/300s"
    inc_dir = tmp_path / "incidents"
    port_base = alloc_ports(span=2300)
    os.environ["PCCLT_INCIDENT_DIR"] = str(inc_dir)
    # one incident per run, deterministically: the rate limiter window
    # outlives the test (a second CONFIRM cycle must only count as
    # suppressed, never fork a second bundle)
    os.environ["PCCLT_INCIDENT_MIN_MS"] = "600000"
    os.environ["PCCLT_MASTER_METRICS_PORT"] = "0"
    master = MasterNode("0.0.0.0", alloc_ports())
    master.run()
    procs = []
    traces = {r: tmp_path / f"exit-{r}.json" for r in range(world)}
    try:
        with wire_topology(world, port_base, mbps=300.0) as envs:
            for r in range(world):
                env = {**envs[r],
                       "PCCLT_WATCHDOG": "1",
                       "PCCLT_TELEMETRY_PUSH_MS": "100",
                       "PCCLT_INCIDENT_DIR": str(inc_dir),
                       "PCCLT_TRACE": str(traces[r])}
                cmd = [sys.executable, str(REPO / "tests" / "chaos_peer.py"),
                       "--master-port", str(master.port), "--rank", str(r),
                       "--world", str(world), "--port-base", str(port_base),
                       "--count", str(count), "--steps", str(steps),
                       "--fault-at", str(fault_at), "--fault", fault,
                       "--env", json.dumps(env)]
                procs.append(subprocess.Popen(cmd, stdout=subprocess.PIPE,
                                              stderr=subprocess.STDOUT,
                                              text=True))
            outs = [p.communicate(timeout=420)[0] for p in procs]
        health = json.loads(_scrape(master.metrics_port, "/health"))
        prom = _scrape(master.metrics_port)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        os.environ.pop("PCCLT_INCIDENT_DIR", None)
        os.environ.pop("PCCLT_INCIDENT_MIN_MS", None)
        os.environ.pop("PCCLT_MASTER_METRICS_PORT", None)
        master.interrupt()
        master.destroy()

    results = {}
    injected_on = None
    for out in outs:
        parsed = None
        for line in out.strip().splitlines():
            try:
                d = json.loads(line)
            except ValueError:
                continue
            if "injected_on" in d:
                injected_on = d["injected_on"]
            if "steps" in d or "error" in d:
                parsed = d
        assert parsed is not None and "error" not in parsed, out[-3000:]
        results[parsed["rank"]] = parsed
    assert set(results) == set(range(world))
    assert injected_on, "victim never injected the fault"

    # --- exactly one incident fired (rate limiter held), watchdog trigger
    assert inc_dir.is_dir(), "incident dir never created"
    bundles = sorted(d for d in inc_dir.iterdir() if d.is_dir())
    assert len(bundles) == 1, bundles
    bundle = bundles[0]
    manifest = json.loads((bundle / "manifest.json").read_text())
    assert manifest["incident_id"] == bundle.name
    assert manifest["trigger"].startswith("watchdog_confirm"), manifest
    assert injected_on in manifest["trigger"], manifest["trigger"]
    assert manifest["health"]["epoch"] == 1

    # --- every live peer contributed a ring dump + stats snapshot under
    # the SAME incident id
    peer_traces = sorted(bundle.glob("peer-*.trace.json"))
    peer_stats = sorted(bundle.glob("peer-*.stats.json"))
    assert len(peer_traces) == world, list(bundle.iterdir())
    assert len(peer_stats) == world, list(bundle.iterdir())
    for sp in peer_stats:
        sj = json.loads(sp.read_text())
        assert sj["incident_id"] == bundle.name
        assert sj["trigger"] == manifest["trigger"]
    for tp in peer_traces:
        tj = json.loads(tp.read_text())
        metas = [e for e in tj["traceEvents"]
                 if e.get("name") == "pcclt_trace_meta"]
        assert metas and metas[0]["args"]["ring_cap"] == 1 << 16

    # --- /health lists the incident; /metrics counts it
    assert health["incidents_total"] == 1, health
    assert [i["id"] for i in health["incidents"]] == [bundle.name]
    assert "pcclt_master_incidents_total 1" in prom

    # --- trace_critic over the peers' full exit dumps: >= 95% of each
    # collective's wall time lands in concrete (peer, stage, edge, phase)
    # segments, and the degraded edge is the critical path
    report = analyze_files([traces[r] for r in range(world)],
                           labels=[f"rank{r}" for r in range(world)])
    agg = report["aggregate"]
    assert agg["ops"] >= steps, agg  # every step attributed
    assert agg["mean_coverage"] >= 0.95, agg
    assert agg["min_coverage"] >= 0.90, agg
    assert agg["critical_edge"] == injected_on, agg
    assert agg["verdicts"].get("stall-straggler", 0) >= 1, agg
    faulted = [c for c in report["collectives"]
               if c["critical_edge"] == injected_on]
    assert faulted, report["collectives"]
    if (d := _artifact_dir()):
        shutil.copytree(bundle, d / "incident" / bundle.name,
                        dirs_exist_ok=True)
        (d / "trace_critic_report.json").write_text(json.dumps(report))


def test_schedule_gauges_on_scrape():
    """Schedule synthesizer fleet introspection (docs/12): after an
    optimize round synthesizes a schedule table for the group, /metrics
    must carry pcclt_schedule_version{group} and one
    pcclt_schedule_kind{group,coll,size_class,algo} series per
    (collective, size-class) cell — promlint-gated like every family."""
    from pccl_tpu.comm import MasterNode

    world = 2
    port_base = alloc_ports(span=2300)
    os.environ["PCCLT_MASTER_METRICS_PORT"] = "0"
    master = MasterNode("0.0.0.0", alloc_ports())
    try:
        master.run()
        mp = master.metrics_port
        peers = [_ObsPeer(master.port, r, world, port_base,
                          {"PCCLT_BENCH_SECONDS": "0.4",
                           "PCCLT_BENCH_CONNECTIONS": "1"},
                          push_ms=150, count=1 << 16, iters=2,
                          optimize=True, hold=True)
                 for r in range(world)]
        try:
            for p in peers:
                p.wait_stats()
            version = {}
            kinds = {}
            deadline = time.time() + 60
            while time.time() < deadline:
                prom = _scrape(mp)
                version = _prom_samples(prom, "pcclt_schedule_version")
                kinds = _prom_samples(prom, "pcclt_schedule_kind")
                if version and kinds:
                    break
                time.sleep(0.3)
            assert version, "pcclt_schedule_version never appeared"
            assert all(v >= 1 for v in version.values()), version
            # one cell per (collective, size-class): 5 colls x 3 classes
            assert len(kinds) == 15, sorted(kinds)
            assert all(v == 1 for v in kinds.values()), kinds
            colls = {dict(k).get("coll") for k in kinds}
            assert colls == {"allreduce", "allgather", "reduce_scatter",
                             "broadcast", "alltoall"}, colls
            algos = {dict(k).get("algo") for k in kinds}
            assert algos <= {"ring", "tree", "butterfly", "mesh",
                             "relay"}, algos
        finally:
            for p in peers:
                p.release()
        for i, p in enumerate(peers):
            assert p.join() == 0, f"peer {i} failed"
    finally:
        os.environ.pop("PCCLT_MASTER_METRICS_PORT", None)
        master.interrupt()
        master.destroy()


def test_straggler_flag_on_netem_degraded_edge():
    """Straggler detection: bandwidth probes (bench ports, un-emulated)
    fill the matrix with fast loopback numbers; the p2p data plane is
    netem-throttled to 40 Mbit/s. The live digests' measured throughput
    sits far below the matrix entry, so /health must flag the edge within
    a push interval or two — while the run keeps completing collectives."""
    from pccl_tpu.comm import MasterNode
    from pccl_tpu.comm.native_bench import _rank_ports

    world, push_ms = 2, 150
    port_base = alloc_ports(span=2300)
    # throttle ONLY the p2p endpoints; bench probe conns stay at loopback
    # speed, so matrix >> measured
    p2p_eps = [f"127.0.0.1:{_rank_ports(port_base, r)[0]}"
               for r in range(world)]
    wire_map = ",".join(f"{ep}=40" for ep in p2p_eps)
    envs = {"PCCLT_WIRE_MBPS_MAP": wire_map,
            "PCCLT_BENCH_SECONDS": "0.4", "PCCLT_BENCH_CONNECTIONS": "1"}
    os.environ["PCCLT_MASTER_METRICS_PORT"] = "0"
    master = MasterNode("0.0.0.0", alloc_ports())
    try:
        master.run()
        mp = master.metrics_port
        peers = [_ObsPeer(master.port, r, world, port_base, envs,
                          push_ms=push_ms, count=1 << 20, iters=3,
                          optimize=True, hold=True)
                 for r in range(world)]
        try:
            flagged = None
            deadline = time.time() + 120
            while time.time() < deadline:
                health = json.loads(_scrape(mp, "/health"))
                bad = [e for e in health["edges"] if e["straggler"]]
                if bad:
                    flagged = (health, bad)
                    break
                if any(p.proc.poll() is not None for p in peers):
                    break
                time.sleep(0.1)
            assert flagged, "no straggler flag within deadline"
            health, bad = flagged
            for e in bad:
                # receiver-witnessed: measured INGRESS far below the matrix
                # entry while the receiver sat blocked on the wire
                assert e["expected_mbps"] > e["rx_mbps"] * 2, e
                assert e["stall_ratio"] >= 0.15, e
                assert e["to"] in p2p_eps, e
            assert health["stragglers_flagged"] >= 1
            # the run was not stopped: peers still finish their ops clean
            stats = [p.wait_stats() for p in peers]
            for s in stats:
                assert s["stats"]["counters"]["collectives_ok"] == 3
            prom = _scrape(mp)
            line = [ln for ln in prom.splitlines()
                    if ln.startswith("pcclt_edge_straggler") and
                    ln.endswith(" 1")]
            assert line, prom[:2000]
        finally:
            for p in peers:
                p.release()
        for i, p in enumerate(peers):
            assert p.join() == 0, f"peer {i} failed"
    finally:
        os.environ.pop("PCCLT_MASTER_METRICS_PORT", None)
        master.interrupt()
        master.destroy()


def test_health_survives_master_sigkill_and_resume(tmp_path):
    """Tier-2/3 HA continuity: /health reports epoch 1 pre-crash; after a
    SIGKILL + journal restart on the same ports the endpoint comes back
    with epoch 2 and the same world, repopulated by resumed peers' fresh
    digests — a master restart is a blip in the fleet view too."""
    journal = str(tmp_path / "master.journal")
    port = alloc_ports()
    mport = alloc_ports()
    base = alloc_ports(64)

    def start_master():
        env = dict(os.environ)
        env["PCCLT_MASTER_METRICS_PORT"] = str(mport)
        proc = subprocess.Popen(
            [sys.executable, "-m", "pccl_tpu.comm.master", "--port",
             str(port), "--journal", journal],
            cwd=str(REPO), env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)
        deadline = time.time() + 20
        while time.time() < deadline:
            try:
                with socket.create_connection(("127.0.0.1", port), timeout=1):
                    return proc
            except OSError:
                assert proc.poll() is None, proc.stdout.read()
                time.sleep(0.05)
        raise RuntimeError("master never started")

    os.environ["PCCLT_TELEMETRY_PUSH_MS"] = "150"
    master = start_master()
    peers = [subprocess.Popen(
        [sys.executable, str(REPO / "tests" / "ha_peer.py"),
         "--master-port", str(port), "--rank", str(r),
         "--base-port", str(base + r * 16), "--steps", "200",
         "--min-world", "3", "--step-interval", "0.15"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for r in range(3)]
    try:
        # world forms, digests flow: /health shows epoch 1 with 3 peers up
        deadline = time.time() + 60
        h1 = None
        while time.time() < deadline:
            try:
                h1 = json.loads(_scrape(mport, "/health"))
                if h1["world_size"] == 3 and \
                        sum(p["up"] for p in h1["peers"]) == 3:
                    break
            except OSError:
                pass
            time.sleep(0.2)
        assert h1 and h1["epoch"] == 1 and h1["world_size"] == 3, h1

        if master.poll() is None:
            master.send_signal(signal.SIGKILL)
        master.wait(timeout=10)
        time.sleep(1.0)  # real outage window
        master = start_master()

        # peers resume; the restarted master's fleet view repopulates with
        # the SAME uuids under epoch 2
        old_uuids = {p["uuid"] for p in h1["peers"]}
        deadline = time.time() + 60
        h2 = None
        while time.time() < deadline:
            try:
                h2 = json.loads(_scrape(mport, "/health"))
                if h2["epoch"] == 2 and h2["world_size"] == 3 and \
                        sum(p["up"] for p in h2["peers"]) == 3:
                    break
            except OSError:
                pass
            time.sleep(0.2)
        assert h2 and h2["epoch"] == 2 and h2["world_size"] == 3, h2
        assert {p["uuid"] for p in h2["peers"] if p["up"]} == old_uuids
    finally:
        os.environ.pop("PCCLT_TELEMETRY_PUSH_MS", None)
        for p in peers:
            if p.poll() is None:
                p.kill()
            p.wait(timeout=10)
        if master.poll() is None:
            master.send_signal(signal.SIGKILL)
        master.wait(timeout=10)
