"""Fleet observability plane (docs/09): live telemetry digests to the
master, the Prometheus /metrics + JSON /health endpoint, cross-peer trace
correlation, and telemetry-driven straggler flagging.

The acceptance scenarios from the three tiers:
  * conservation through aggregation — a LIVE scrape of the master's
    /metrics during a netem 4-peer run must report per-edge byte totals
    that agree exactly with the peers' own stats() counters;
  * a master SIGKILL + journal restart preserves /health continuity (the
    epoch survives and bumps, peers reappear via resumed sessions);
  * a netem-degraded edge (fast bandwidth probes, throttled data plane)
    raises the straggler flag in /health within a push interval, without
    stopping the run;
  * tools/trace_merge aligns per-peer Chrome traces on (epoch, seq).

Multi-peer behavior runs real processes, never mocks (the repo's
stress-test discipline)."""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.request
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
LIB = REPO / "pccl_tpu" / "native" / "build" / "libpcclt.so"
pytestmark = pytest.mark.skipif(not LIB.exists(), reason="native lib not built")

from conftest import alloc_ports  # noqa: E402


def _scrape(port: int, path: str = "/metrics", timeout: float = 5.0) -> str:
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}",
                                timeout=timeout) as r:
        return r.read().decode()


def _prom_samples(text: str, name: str) -> dict:
    """{frozenset(label items): float value} for one metric family."""
    out = {}
    for line in text.splitlines():
        if not line.startswith(name + "{"):
            continue
        labels, _, value = line[len(name) + 1:].partition("} ")
        items = []
        for part in labels.split('",'):
            k, _, v = part.partition('="')
            items.append((k, v.rstrip('"')))
        out[frozenset(items)] = float(value)
    return out


# ---------------------------------------------------------------- tier 3


def test_trace_merge_alignment(tmp_path):
    """Two synthetic peer traces whose clocks disagree by 5 seconds merge
    onto one timeline: spans sharing (epoch, seq) end at the same merged
    timestamp, pids stay distinct, process names keep their peer prefix."""
    from tools.trace_merge import merge_files

    def trace(base_us, peer):
        evs = [{"ph": "M", "name": "process_name", "pid": 1,
                "args": {"name": "pcclt native"}}]
        for seq in (11, 12, 13):
            t = base_us + seq * 1000.0
            evs.append({"name": "allreduce", "cat": "collective", "ph": "X",
                        "pid": 1, "tid": 7, "ts": t, "dur": 400.0 + peer,
                        "args": {"seq": seq, "epoch": 2}})
        # an unanchored python-side section rides along untouched
        evs.append({"name": "py/step", "ph": "X", "pid": 0, "tid": 1,
                    "ts": base_us, "dur": 5000.0, "args": {}})
        return {"traceEvents": evs}

    a, b = tmp_path / "a.json", tmp_path / "b.json"
    a.write_text(json.dumps(trace(1_000_000.0, 0)))
    b.write_text(json.dumps(trace(6_000_000.0, 1)))  # clock 5 s ahead
    merged = merge_files([a, b])
    meta = merged["metadata"]
    assert meta["shared_anchors"]["b"] == 3
    assert abs(meta["offsets_us"]["b"] + 5_000_000.0) < 2.0
    ends = {}
    for e in merged["traceEvents"]:
        if e.get("name") == "allreduce":
            key = (e["args"]["epoch"], e["args"]["seq"], e["pid"])
            ends[key] = e["ts"] + e["dur"]
    # per (epoch, seq): both peers' spans end within the dur skew we built
    for seq in (11, 12, 13):
        per_seq = [v for (ep, s, _), v in ends.items() if s == seq]
        assert len(per_seq) == 2
        assert abs(per_seq[0] - per_seq[1]) <= 1.5
    pids = {e.get("pid") for e in merged["traceEvents"] if "pid" in e}
    assert len(pids) == 4  # (2 peers) x (python pid 0 + native pid 1)
    names = [e["args"]["name"] for e in merged["traceEvents"]
             if e.get("name") == "process_name"]
    assert any(n.startswith("a: ") for n in names)
    assert any(n.startswith("b: ") for n in names)


def test_trace_merge_cli_rejects_unanchored(tmp_path):
    """Merging traces that share no collective anchor must fail loudly
    (exit 1), not produce a silently misaligned artifact."""
    a, b = tmp_path / "a.json", tmp_path / "b.json"
    a.write_text(json.dumps({"traceEvents": [
        {"name": "allreduce", "ph": "X", "pid": 1, "tid": 1, "ts": 1.0,
         "dur": 2.0, "args": {"seq": 1}}]}))
    b.write_text(json.dumps({"traceEvents": [
        {"name": "py/step", "ph": "X", "pid": 0, "tid": 1, "ts": 9.0,
         "dur": 2.0, "args": {}}]}))
    r = subprocess.run(
        [sys.executable, "-m", "tools.trace_merge", str(a), str(b),
         "-o", str(tmp_path / "out.json")],
        cwd=str(REPO), capture_output=True, text=True)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "no shared collective anchors" in r.stderr
    r = subprocess.run(
        [sys.executable, "-m", "tools.trace_merge", str(a), str(b),
         "--allow-unanchored", "-o", str(tmp_path / "out.json")],
        cwd=str(REPO), capture_output=True, text=True)
    assert r.returncode == 0, r.stdout + r.stderr
    assert (tmp_path / "out.json").exists()


def test_stats_exposes_digest_and_ring_drop_counters():
    """stats() carries the new observability counters, and the trace dump
    header (pcclt_trace_meta) reports ring accounting."""
    from pccl_tpu.comm import (Communicator, MasterNode, trace_clear,
                               trace_enable, trace_events)

    master = MasterNode("0.0.0.0", alloc_ports())
    master.run()
    try:
        comm = Communicator("127.0.0.1", master.port,
                            p2p_port=alloc_ports(span=64))
        comm.connect()
        s = comm.stats()["counters"]
        # push cadence not configured in this process: counter present, 0
        assert s["telemetry_digests"] == 0
        assert s["trace_ring_dropped"] == 0
        trace_enable(True)
        evs = comm.trace_events()
        meta = [e for e in evs if e.get("name") == "pcclt_trace_meta"]
        assert meta, "trace dump header missing"
        args = meta[0]["args"]
        assert {"captured", "pushed", "dropped", "ring_cap",
                "epoch"} <= set(args)
        assert args["dropped"] == 0
        assert args["epoch"] >= 1  # stamped at welcome
        # health is served through the C API even with HTTP disabled
        h = master.health()
        assert h["epoch"] == 1
        assert master.metrics_port == 0
        comm.destroy()
        trace_enable(False)
        trace_clear()
    finally:
        master.interrupt()
        master.destroy()


# ------------------------------------------------- live multi-process tiers


class _ObsPeer:
    def __init__(self, master_port, rank, world, port_base, envs, **kw):
        cmd = [sys.executable, str(REPO / "tests" / "obs_peer.py"),
               "--master-port", str(master_port), "--rank", str(rank),
               "--world", str(world), "--port-base", str(port_base),
               "--env", json.dumps(envs)]
        for k, v in kw.items():
            flag = f"--{k.replace('_', '-')}"
            if v is True:
                cmd.append(flag)
            elif v is not False and v is not None:
                cmd += [flag, str(v)]
        self.proc = subprocess.Popen(cmd, stdin=subprocess.PIPE,
                                     stdout=subprocess.PIPE,
                                     stderr=subprocess.STDOUT, text=True)

    def wait_stats(self, timeout=120):
        """Read lines until the stats JSON appears (peer then holds)."""
        deadline = time.time() + timeout
        line = ""
        while time.time() < deadline:
            line = self.proc.stdout.readline()
            if not line:
                raise AssertionError("peer exited before printing stats")
            line = line.strip()
            if line.startswith("{"):
                d = json.loads(line)
                assert "error" not in d, d
                return d
        raise AssertionError(f"no stats line within {timeout}s: {line}")

    def release(self):
        try:
            self.proc.stdin.write("go\n")
            self.proc.stdin.flush()
        except (BrokenPipeError, OSError):
            pass

    def join(self, timeout=60):
        return self.proc.wait(timeout=timeout)

    def kill(self):
        if self.proc.poll() is None:
            self.proc.kill()
        self.proc.wait(timeout=10)


def _artifact_dir():
    d = os.environ.get("OBS_ARTIFACT_DIR")
    return Path(d) if d else None


def test_metrics_conservation_live_scrape(tmp_path):
    """The tier-2/3 acceptance: a 4-peer netem world with digests on; a
    LIVE /metrics scrape must agree exactly with every peer's stats()
    per-edge byte totals, and the per-peer traces merge into one fleet
    timeline on (epoch, seq)."""
    from pccl_tpu.comm import MasterNode
    from pccl_tpu.comm.native_bench import _rank_ports, wire_topology
    from tools.trace_merge import merge_files

    world, count, push_ms = 4, 1 << 18, 150
    port_base = alloc_ports(span=2300)
    os.environ["PCCLT_MASTER_METRICS_PORT"] = "0"
    master = MasterNode("0.0.0.0", alloc_ports())
    try:
        master.run()
        mp = master.metrics_port
        assert mp > 0
        peers = []
        traces = [tmp_path / f"peer{r}.json" for r in range(world)]
        with wire_topology(world, port_base, mbps=4000.0) as envs:
            for r in range(world):
                peers.append(_ObsPeer(master.port, r, world, port_base,
                                      envs[r], push_ms=push_ms, count=count,
                                      iters=3, hold=True,
                                      trace_out=str(traces[r])))
            try:
                stats = {}
                for r, p in enumerate(peers):
                    stats[r] = p.wait_stats()["stats"]

                # peers alive and holding: scrape LIVE
                nbytes = count * 4
                expected_per_peer = 3 * 2 * (world - 1) * nbytes // world
                deadline = time.time() + 30
                while True:
                    prom = _scrape(mp)
                    tx = _prom_samples(prom, "pcclt_edge_tx_bytes_total")
                    total = sum(tx.values())
                    if total == world * expected_per_peer:
                        break
                    assert time.time() < deadline, \
                        f"scrape never converged: {total} != " \
                        f"{world * expected_per_peer}\n{prom[:2000]}"
                    time.sleep(0.2)

                # exact per-edge agreement: every peer edge appears in the
                # scrape with the same cumulative byte counters
                rx = _prom_samples(prom, "pcclt_edge_rx_bytes_total")
                endpoint_of = {r: f"127.0.0.1:{_rank_ports(port_base, r)[0]}"
                               for r in range(world)}
                for r in range(world):
                    for ep, e in stats[r]["edges"].items():
                        match = [v for k, v in tx.items()
                                 if ("to", ep) in k]
                        assert e["tx_bytes"] in match, (r, ep, e, tx)
                        match_rx = [v for k, v in rx.items()
                                    if ("to", ep) in k]
                        assert e["rx_bytes"] in match_rx
                # all four peers report in /health, all up
                health = json.loads(_scrape(mp, "/health"))
                ups = [p for p in health["peers"] if p["up"]]
                assert len(ups) == world, health
                assert health["telemetry_digests"] >= world
                assert all(p["last_seq"] >= 3 for p in ups), health
                if (d := _artifact_dir()):
                    (d / "fleet_health.json").write_text(json.dumps(health))
                    (d / "metrics.prom").write_text(prom)
            finally:
                for p in peers:
                    p.release()
            for i, p in enumerate(peers):
                assert p.join() == 0, f"peer {i} failed"
    finally:
        os.environ.pop("PCCLT_MASTER_METRICS_PORT", None)
        master.interrupt()
        master.destroy()

    # tier-3 correlation: the four dumps merge into ONE aligned timeline
    merged = merge_files(traces)
    meta = merged["metadata"]
    assert all(n >= 3 for n in meta["shared_anchors"].values()), meta
    by_key = {}
    for e in merged["traceEvents"]:
        if e.get("name") == "allreduce":
            args = e.get("args", {})
            by_key.setdefault((args.get("epoch"), args["seq"]),
                              []).append(e["ts"] + e["dur"])
    full = {k: v for k, v in by_key.items() if len(v) == world}
    assert full, f"no (epoch, seq) shared by all peers: {by_key}"
    for key, ends in full.items():
        # collectives complete near-simultaneously: after alignment all
        # peers' op ends for one (epoch, seq) sit within a second
        assert max(ends) - min(ends) < 1e6, (key, ends)
    if (d := _artifact_dir()):
        (d / "fleet_trace.json").write_text(json.dumps(merged))


def test_straggler_flag_on_netem_degraded_edge():
    """Straggler detection: bandwidth probes (bench ports, un-emulated)
    fill the matrix with fast loopback numbers; the p2p data plane is
    netem-throttled to 40 Mbit/s. The live digests' measured throughput
    sits far below the matrix entry, so /health must flag the edge within
    a push interval or two — while the run keeps completing collectives."""
    from pccl_tpu.comm import MasterNode
    from pccl_tpu.comm.native_bench import _rank_ports

    world, push_ms = 2, 150
    port_base = alloc_ports(span=2300)
    # throttle ONLY the p2p endpoints; bench probe conns stay at loopback
    # speed, so matrix >> measured
    p2p_eps = [f"127.0.0.1:{_rank_ports(port_base, r)[0]}"
               for r in range(world)]
    wire_map = ",".join(f"{ep}=40" for ep in p2p_eps)
    envs = {"PCCLT_WIRE_MBPS_MAP": wire_map,
            "PCCLT_BENCH_SECONDS": "0.4", "PCCLT_BENCH_CONNECTIONS": "1"}
    os.environ["PCCLT_MASTER_METRICS_PORT"] = "0"
    master = MasterNode("0.0.0.0", alloc_ports())
    try:
        master.run()
        mp = master.metrics_port
        peers = [_ObsPeer(master.port, r, world, port_base, envs,
                          push_ms=push_ms, count=1 << 20, iters=3,
                          optimize=True, hold=True)
                 for r in range(world)]
        try:
            flagged = None
            deadline = time.time() + 120
            while time.time() < deadline:
                health = json.loads(_scrape(mp, "/health"))
                bad = [e for e in health["edges"] if e["straggler"]]
                if bad:
                    flagged = (health, bad)
                    break
                if any(p.proc.poll() is not None for p in peers):
                    break
                time.sleep(0.1)
            assert flagged, "no straggler flag within deadline"
            health, bad = flagged
            for e in bad:
                # receiver-witnessed: measured INGRESS far below the matrix
                # entry while the receiver sat blocked on the wire
                assert e["expected_mbps"] > e["rx_mbps"] * 2, e
                assert e["stall_ratio"] >= 0.15, e
                assert e["to"] in p2p_eps, e
            assert health["stragglers_flagged"] >= 1
            # the run was not stopped: peers still finish their ops clean
            stats = [p.wait_stats() for p in peers]
            for s in stats:
                assert s["stats"]["counters"]["collectives_ok"] == 3
            prom = _scrape(mp)
            line = [ln for ln in prom.splitlines()
                    if ln.startswith("pcclt_edge_straggler") and
                    ln.endswith(" 1")]
            assert line, prom[:2000]
        finally:
            for p in peers:
                p.release()
        for i, p in enumerate(peers):
            assert p.join() == 0, f"peer {i} failed"
    finally:
        os.environ.pop("PCCLT_MASTER_METRICS_PORT", None)
        master.interrupt()
        master.destroy()


def test_health_survives_master_sigkill_and_resume(tmp_path):
    """Tier-2/3 HA continuity: /health reports epoch 1 pre-crash; after a
    SIGKILL + journal restart on the same ports the endpoint comes back
    with epoch 2 and the same world, repopulated by resumed peers' fresh
    digests — a master restart is a blip in the fleet view too."""
    journal = str(tmp_path / "master.journal")
    port = alloc_ports()
    mport = alloc_ports()
    base = alloc_ports(64)

    def start_master():
        env = dict(os.environ)
        env["PCCLT_MASTER_METRICS_PORT"] = str(mport)
        proc = subprocess.Popen(
            [sys.executable, "-m", "pccl_tpu.comm.master", "--port",
             str(port), "--journal", journal],
            cwd=str(REPO), env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)
        deadline = time.time() + 20
        while time.time() < deadline:
            try:
                with socket.create_connection(("127.0.0.1", port), timeout=1):
                    return proc
            except OSError:
                assert proc.poll() is None, proc.stdout.read()
                time.sleep(0.05)
        raise RuntimeError("master never started")

    os.environ["PCCLT_TELEMETRY_PUSH_MS"] = "150"
    master = start_master()
    peers = [subprocess.Popen(
        [sys.executable, str(REPO / "tests" / "ha_peer.py"),
         "--master-port", str(port), "--rank", str(r),
         "--base-port", str(base + r * 16), "--steps", "200",
         "--min-world", "3", "--step-interval", "0.15"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for r in range(3)]
    try:
        # world forms, digests flow: /health shows epoch 1 with 3 peers up
        deadline = time.time() + 60
        h1 = None
        while time.time() < deadline:
            try:
                h1 = json.loads(_scrape(mport, "/health"))
                if h1["world_size"] == 3 and \
                        sum(p["up"] for p in h1["peers"]) == 3:
                    break
            except OSError:
                pass
            time.sleep(0.2)
        assert h1 and h1["epoch"] == 1 and h1["world_size"] == 3, h1

        if master.poll() is None:
            master.send_signal(signal.SIGKILL)
        master.wait(timeout=10)
        time.sleep(1.0)  # real outage window
        master = start_master()

        # peers resume; the restarted master's fleet view repopulates with
        # the SAME uuids under epoch 2
        old_uuids = {p["uuid"] for p in h1["peers"]}
        deadline = time.time() + 60
        h2 = None
        while time.time() < deadline:
            try:
                h2 = json.loads(_scrape(mport, "/health"))
                if h2["epoch"] == 2 and h2["world_size"] == 3 and \
                        sum(p["up"] for p in h2["peers"]) == 3:
                    break
            except OSError:
                pass
            time.sleep(0.2)
        assert h2 and h2["epoch"] == 2 and h2["world_size"] == 3, h2
        assert {p["uuid"] for p in h2["peers"] if p["up"]} == old_uuids
    finally:
        os.environ.pop("PCCLT_TELEMETRY_PUSH_MS", None)
        for p in peers:
            if p.poll() is None:
                p.kill()
            p.wait(timeout=10)
        if master.poll() is None:
            master.send_signal(signal.SIGKILL)
        master.wait(timeout=10)
