"""Master-HA test peer (subprocess worker for tests/test_master_ha.py).

Unlike tests/ft_peer.py — which recovers from master loss by REJOINING with
a fresh communicator — this peer relies entirely on the native session
resume: the master may be SIGKILLed and restarted (with a journal) under
it, and every step must complete under the ORIGINAL uuid. Any
MasterUnreachableError/KickedError is fatal (exit 4): with the journal +
resume enabled a master restart must be a blip, never an identity reset.

Each step runs one shared-state sync (deterministic content, lockstep
revision) and one all-reduce, then prints a machine-parsable line:

    STEP <n> rev=<revision> world=<w> resumes=<k> epoch=<e> \
        ss_rx=<bytes> ss_tx=<bytes> conns=<p2p edge connects>

The test asserts from these lines: revision monotonicity across the
outage, zero sync bytes moved post-resume (no full shared-state
retransmit), stable p2p connect counts (mesh kept alive), and a bumped
epoch with resumes >= 1.
"""

from __future__ import annotations

import argparse
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np  # noqa: E402


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--master-port", type=int, required=True)
    ap.add_argument("--base-port", type=int, required=True)
    ap.add_argument("--rank", type=int, default=0)
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--min-world", type=int, default=2)
    ap.add_argument("--step-interval", type=float, default=0.1)
    ap.add_argument("--count", type=int, default=16384)
    ap.add_argument("--reconnect-attempts", type=int, default=12)
    ap.add_argument("--reconnect-backoff-ms", type=int, default=100)
    ap.add_argument("--reconnect-cap-ms", type=int, default=1000)
    args = ap.parse_args()

    from pccl_tpu.comm import (
        Communicator,
        ConnectionLostError,
        KickedError,
        MasterUnreachableError,
        OperationAbortedError,
        PcclError,
        ReduceOp,
        SharedState,
        TensorInfo,
        TooFewPeersError,
    )

    comm = None
    deadline = time.time() + 60
    while True:
        comm = Communicator("127.0.0.1", args.master_port,
                            p2p_port=args.base_port,
                            ss_port=args.base_port + 4,
                            bench_port=args.base_port + 8,
                            reconnect_attempts=args.reconnect_attempts,
                            reconnect_backoff_ms=args.reconnect_backoff_ms,
                            reconnect_backoff_cap_ms=args.reconnect_cap_ms)
        try:
            comm.connect()
            break
        except PcclError:
            comm.destroy()
            if time.time() > deadline:
                print("FATAL connect timeout", flush=True)
                return 2
            time.sleep(0.3)

    while comm.world_size < args.min_world:
        if time.time() > deadline:
            print("TIMEOUT waiting for world", flush=True)
            return 2
        try:
            if comm.are_peers_pending():
                comm.update_topology()
        except (MasterUnreachableError, KickedError) as e:
            print(f"FATAL {type(e).__name__} during formation", flush=True)
            return 4
        except PcclError:
            pass
        time.sleep(0.02)

    # shared state: deterministic lockstep content so a healthy world syncs
    # with ZERO bytes moved (all hashes equal); the step count drives the
    # revision, so every peer offers the same revision each step
    state_arr = np.zeros(args.count, dtype=np.float32)
    x = np.ones(args.count, dtype=np.float32)
    y = np.empty_like(x)

    step = 0
    rev = 0
    while step < args.steps:
        # admit pending joiners (none expected in this harness, but keeps
        # the loop shaped like real training)
        try:
            if comm.are_peers_pending():
                comm.update_topology()
        except (MasterUnreachableError, KickedError) as e:
            print(f"FATAL {type(e).__name__}", flush=True)
            return 4
        except PcclError:
            time.sleep(0.05)
            continue

        target_rev = rev + 1
        state_arr[:] = float(target_rev)  # same bytes on every peer
        ss_rx = ss_tx = 0
        try:
            info = comm.sync_shared_state(SharedState(
                [TensorInfo.from_numpy("w", state_arr)], revision=target_rev))
            rev = info.revision
            ss_rx, ss_tx = info.rx_bytes, info.tx_bytes
        except (MasterUnreachableError, KickedError) as e:
            print(f"FATAL {type(e).__name__}", flush=True)
            return 4
        except (ConnectionLostError, OperationAbortedError):
            # the round died with the old master. If the resume ack says the
            # revision completed group-wide just before the crash, adopt it;
            # otherwise retry the same revision on the resumed session.
            if comm.shared_state_revision >= target_rev:
                rev = comm.shared_state_revision
            else:
                time.sleep(0.05)
                continue

        try:
            info = comm.all_reduce(x, y, op=ReduceOp.SUM)
            world = info.world_size
        except (MasterUnreachableError, KickedError) as e:
            print(f"FATAL {type(e).__name__}", flush=True)
            return 4
        except (ConnectionLostError, OperationAbortedError):
            try:
                comm.update_topology()
            except (MasterUnreachableError, KickedError) as e:
                print(f"FATAL {type(e).__name__}", flush=True)
                return 4
            except PcclError:
                time.sleep(0.05)
            continue
        except TooFewPeersError:
            print("FATAL TooFewPeersError (world must never shrink here)",
                  flush=True)
            return 4
        if abs(float(y[0]) - world) > 1e-5:
            print(f"WRONG RESULT step={step} y={y[0]} world={world}",
                  flush=True)
            return 3

        conns = sum(e["connects"] for e in comm.stats()["edges"].values())
        print(f"STEP {step} rev={rev} world={world} "
              f"resumes={comm.reconnect_count} epoch={comm.master_epoch} "
              f"ss_rx={ss_rx} ss_tx={ss_tx} conns={conns}", flush=True)
        step += 1
        if args.step_interval > 0:
            time.sleep(args.step_interval)

    comm.destroy()
    print("DONE", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
