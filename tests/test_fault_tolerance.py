"""Fault tolerance under peer churn: crash mid-run, late join, SIGKILL.

Reference parity: the stress-test orchestrators
(/root/reference/python/tests/stress_tests/basic_stress_test/
stresstest_orchestrator.py) launch a master + real peer processes on
loopback, kill peers mid-run, and watch stdout heartbeats — multi-peer
behavior is tested with real processes, never mocks (SURVEY.md §4).
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import threading
import time
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
PEER = REPO / "tests" / "ft_peer.py"
LIB = REPO / "pccl_tpu" / "native" / "build" / "libpcclt.so"
pytestmark = pytest.mark.skipif(not LIB.exists(), reason="native lib not built")


class PeerProc:
    """Subprocess peer with a live stdout line buffer."""

    def __init__(self, master_port: int, rank: int, base_port: int,
                 env: dict | None = None, **kw):
        cmd = [sys.executable, str(PEER), "--master-port", str(master_port),
               "--rank", str(rank), "--base-port", str(base_port)]
        for k, v in kw.items():
            cmd += [f"--{k.replace('_', '-')}", str(v)]
        self.proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                                     stderr=subprocess.STDOUT, text=True,
                                     env=env)
        self.lines: list[str] = []
        self._t = threading.Thread(target=self._pump, daemon=True)
        self._t.start()

    def _pump(self) -> None:
        assert self.proc.stdout is not None
        for line in self.proc.stdout:
            self.lines.append(line.rstrip())

    def wait_for_step(self, step: int, timeout: float = 120) -> bool:
        deadline = time.time() + timeout
        while time.time() < deadline:
            if any(ln.startswith(f"STEP {step} ") for ln in self.lines):
                return True
            if self.proc.poll() is not None:
                return any(ln.startswith(f"STEP {step} ") for ln in self.lines)
            time.sleep(0.05)
        return False

    @staticmethod
    def _world_of(line: str) -> int:
        return int(line.split("world=")[1].split()[0])

    def last_world(self) -> int:
        for ln in reversed(self.lines):
            if ln.startswith("STEP "):
                return self._world_of(ln)
        return -1

    def worlds(self) -> set[int]:
        return {self._world_of(ln) for ln in self.lines
                if ln.startswith("STEP ")}

    def kill(self) -> None:
        if self.proc.poll() is None:
            self.proc.send_signal(signal.SIGKILL)
        self.proc.wait(timeout=10)

    def join(self, timeout: float = 120) -> int:
        try:
            return self.proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            self.proc.kill()
            raise


from conftest import alloc_ports as _next_port


@pytest.fixture
def master():
    from pccl_tpu.comm import MasterNode

    m = MasterNode("0.0.0.0", _next_port())
    m.run()
    yield m
    m.interrupt()
    m.destroy()


def test_survivors_recover_from_sigkill(master):
    """SIGKILL one of three peers mid-run; the other two must finish all
    steps with correct sums over the shrunken world (reference recovery
    protocol: abort broadcast -> p2p re-establish -> caller retry)."""
    base = _next_port(64)
    peers = [PeerProc(master.port, r, base + r * 16, steps=30, min_world=3,
                      step_interval=0.2)
             for r in range(3)]
    try:
        assert peers[2].wait_for_step(5), f"peer2 stalled: {peers[2].lines[-5:]}"
        peers[2].kill()
        assert peers[0].join() == 0, f"peer0 failed: {peers[0].lines[-10:]}"
        assert peers[1].join() == 0, f"peer1 failed: {peers[1].lines[-10:]}"
        # after the kill the survivors' world must have shrunk to 2
        assert peers[0].last_world() == 2
        assert peers[1].last_world() == 2
    finally:
        for p in peers:
            p.kill()


def test_abrupt_exit_mid_run(master):
    """A peer that os._exit()s without goodbye (reference stresstest_peer
    exit(0) pattern) must not wedge the group."""
    base = _next_port(64)
    peers = [PeerProc(master.port, 0, base, steps=25, min_world=2),
             PeerProc(master.port, 1, base + 16, steps=25, min_world=2,
                      die_at=6)]
    try:
        assert peers[1].join() == 0
        assert peers[0].join() == 0, f"survivor failed: {peers[0].lines[-10:]}"
        assert peers[0].last_world() == 1  # finished alone
    finally:
        for p in peers:
            p.kill()


def test_churn_soak_smoke():
    """Short run of the stress orchestrator (examples/stress): random peer
    deaths + relaunches must keep the group progressing."""
    proc = subprocess.run(
        [sys.executable, str(REPO / "examples" / "stress" / "stress_orchestrator.py"),
         "--duration", "30", "--peers", "3", "--die-prob", "0.01",
         "--master-port", str(_next_port()), "--base-port", str(_next_port(64)),
         # 1-core CI: peer relaunch startup can eat a short stall window, so
         # rely on the orchestrator's zero-total-progress check instead
         "--stall-seconds", "60"],
        capture_output=True, text=True, timeout=180)
    assert proc.returncode == 0, \
        f"soak failed:\nstdout:{proc.stdout[-1500:]}\nstderr:{proc.stderr[-1500:]}"
    assert "SOAK PASSED" in proc.stdout


def test_master_churn_soak_smoke():
    """Master-kill soak: the MASTER process is SIGKILLed and restarted on a
    schedule while peers churn too; peers must rejoin (fresh communicator
    against the restarted master, revision-0 resume) and the group must keep
    making progress (reference recipe: docs/md/05-ImplementationNotes/
    03_MasterOrchestration.md — restart master, peers reconnect, resume)."""
    proc = subprocess.run(
        [sys.executable, str(REPO / "examples" / "stress" / "stress_orchestrator.py"),
         "--duration", "45", "--peers", "3", "--die-prob", "0.003",
         "--master-kill-interval", "15",
         "--master-port", str(_next_port()), "--base-port", str(_next_port(64)),
         "--stall-seconds", "60"],
        capture_output=True, text=True, timeout=240)
    assert proc.returncode == 0, \
        f"soak failed:\nstdout:{proc.stdout[-1500:]}\nstderr:{proc.stderr[-1500:]}"
    assert "SOAK PASSED" in proc.stdout
    assert "master restarts" in proc.stdout


def test_late_joiner_is_admitted(master):
    """A peer joining mid-training must be admitted by the running peers'
    update_topology votes and participate in subsequent reduces."""
    base = _next_port(64)
    peers = [PeerProc(master.port, 0, base, steps=60, min_world=2,
                      step_interval=0.25),
             PeerProc(master.port, 1, base + 16, steps=60, min_world=2,
                      step_interval=0.25)]
    late = None
    try:
        assert peers[0].wait_for_step(3)
        late = PeerProc(master.port, 2, base + 32, steps=10, min_world=3)
        assert late.join() == 0, f"late joiner failed: {late.lines[-10:]}"
        assert late.last_world() == 3, f"late joiner world: {late.lines[-5:]}"
        assert peers[0].join() == 0, f"peer0 failed: {peers[0].lines[-10:]}"
        assert peers[1].join() == 0, f"peer1 failed: {peers[1].lines[-10:]}"
        # the incumbents must have seen world=3 while the joiner was in
        assert any("world=3" in ln for ln in peers[0].lines)
    finally:
        for p in peers + ([late] if late else []):
            p.kill()


def test_vote_vs_commence_no_deadlock():
    """Regression: one peer parked in a collective commence while the other
    votes update_topology used to cross-wait forever (the vote waits for the
    initiator, the commence waits for the voter). The master must DEFER the
    vote (kM2CTopologyDeferred): update_topology returns no-op, the voter
    joins the collective, and both finish."""
    import numpy as np

    from pccl_tpu.comm import Communicator, MasterNode, ReduceOp

    master = MasterNode("0.0.0.0", _next_port())
    master.run()
    base = _next_port(64)
    comms, errors = [], []

    def mk(rank):
        c = Communicator("127.0.0.1", master.port, p2p_port=base + rank * 8,
                         ss_port=base + 256 + rank * 8,
                         bench_port=base + 512 + rank * 8)
        c.connect()
        return c

    try:
        # connect concurrently: a pending joiner is only admitted once an
        # incumbent votes, so b's connect() blocks until a's admit loop runs
        slots = {}

        def joiner(rank):
            try:
                slots[rank] = mk(rank)
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=joiner, args=(r,)) for r in range(2)]
        for th in threads:
            th.start()
        deadline = time.time() + 60
        while len(slots) < 2 or any(c.world_size < 2 for c in slots.values()):
            assert time.time() < deadline, f"world never formed: {errors}"
            for c in list(slots.values()):
                if c.are_peers_pending():
                    c.update_topology()
            time.sleep(0.02)
        for th in threads:
            th.join()
        assert not errors, f"connect failed: {errors}"
        a, b = slots[0], slots[1]
        comms.extend([a, b])

        n = 1 << 16
        results = {}

        def reduce_b():
            try:
                x = np.full(n, 2.0, dtype=np.float32)
                b.all_reduce(x, x, op=ReduceOp.SUM)  # parks awaiting commence
                results["b"] = float(x[0])
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        t = threading.Thread(target=reduce_b)
        t.start()
        time.sleep(0.5)  # b is now parked in the commence wait

        # without the tie-break this vote deadlocks the group
        t0 = time.time()
        a.update_topology()  # must return promptly (deferred no-op)
        assert time.time() - t0 < 30, "update_topology wedged"

        x = np.full(n, 1.0, dtype=np.float32)
        a.all_reduce(x, x, op=ReduceOp.SUM)
        results["a"] = float(x[0])
        t.join(timeout=60)
        assert not t.is_alive(), "peer b never unparked"
        assert not errors, f"peer b failed: {errors}"
        assert results == {"a": 3.0, "b": 3.0}
    finally:
        for c in comms:
            c.destroy()
        master.interrupt()
        master.destroy()


def test_master_survives_protocol_garbage():
    """Robustness: raw garbage, truncated frames, huge declared lengths, and
    valid-type/malformed-payload packets at the master port must never kill
    the master; a legitimate peer must still join and reduce afterwards."""
    import socket
    import struct

    import numpy as np

    from pccl_tpu.comm import Communicator, MasterNode, ReduceOp

    master = MasterNode("0.0.0.0", _next_port())
    master.run()
    try:
        attacks = [
            b"\x00" * 64,                       # zero frames
            b"GET / HTTP/1.1\r\n\r\n",          # wrong protocol entirely
            struct.pack(">IH", 2 + 6, 0x1001),  # hello with missing payload
            struct.pack(">IH", 0xFFFFFFF, 0x1001),  # absurd declared length
            struct.pack(">IH", 2 + 4, 0x1004) + b"\x01\x02\x03\x04",  # short established
            struct.pack(">IH", 2, 0x9999),      # unknown type, empty payload
            bytes(range(256)),                  # binary noise
        ]
        for payload in attacks:
            with socket.create_connection(("127.0.0.1", master.port),
                                          timeout=5) as s:
                s.sendall(payload)
                s.settimeout(0.3)
                try:
                    s.recv(256)
                except (TimeoutError, OSError):
                    pass
        # instant connect+close probes (the accept-race regression shape)
        for _ in range(20):
            socket.create_connection(("127.0.0.1", master.port), timeout=5).close()

        base = _next_port(32)
        comm = Communicator("127.0.0.1", master.port, p2p_port=base,
                            ss_port=base + 4, bench_port=base + 8)
        comm.connect()  # master must still be alive and sane
        assert comm.world_size == 1
        x = np.ones(16, np.float32)
        try:
            comm.all_reduce(x, x, op=ReduceOp.SUM)
        except Exception:  # noqa: BLE001 — solo reduce returns TooFewPeers
            pass
        comm.destroy()
    finally:
        master.interrupt()
        master.destroy()


def test_quantized_churn_recovery(master):
    """SIGKILL a peer mid-run while the group reduces over the QUANTIZED
    wire path: the abort/restore machinery must recover it exactly like the
    fp32 path (quantized sends ride scratch buffers with their own restore
    semantics, so churn coverage is separate)."""
    base = _next_port(64)
    peers = [PeerProc(master.port, r, base + r * 16, steps=25, min_world=3,
                      step_interval=0.2, quantize="minmax")
             for r in range(3)]
    try:
        assert peers[2].wait_for_step(4), f"peer2 stalled: {peers[2].lines[-5:]}"
        peers[2].kill()
        assert peers[0].join() == 0, f"peer0 failed: {peers[0].lines[-10:]}"
        assert peers[1].join() == 0, f"peer1 failed: {peers[1].lines[-10:]}"
        assert peers[0].last_world() == 2
        assert peers[1].last_world() == 2
    finally:
        for p in peers:
            p.kill()


def test_peer_group_isolation_under_churn(master):
    """Grid pattern under churn: killing a peer in group 0 must not change
    group 1's WORLD — every group-1 step completes over its own 2-world
    while group 0's survivor degrades to solo. (Membership/topology rounds
    are global, so group-1 ops may transiently retry during the
    re-establish; what must never leak across groups is the world
    accounting this asserts.)"""
    base = _next_port(96)
    g0 = [PeerProc(master.port, r, base + r * 16, steps=30, min_world=2,
                   step_interval=0.2, peer_group=0) for r in range(2)]
    g1 = [PeerProc(master.port, 2 + r, base + 32 + r * 16, steps=30,
                   min_world=2, step_interval=0.2, peer_group=1)
          for r in range(2)]
    try:
        assert g0[0].wait_for_step(4), f"g0 stalled: {g0[0].lines[-5:]}"
        assert g1[0].wait_for_step(4), f"g1 stalled: {g1[0].lines[-5:]}"
        g0[1].kill()
        # group 1 completes at full strength; group 0's survivor finishes.
        # EVERY group-1 step must be world=2: a transient drop would mean
        # group 0's churn leaked across the group boundary.
        for p in g1:
            assert p.join() == 0, f"group-1 peer failed: {p.lines[-10:]}"
            assert p.worlds() == {2}, f"group-1 disturbed: {p.worlds()}"
        assert g0[0].join() == 0, f"group-0 survivor failed: {g0[0].lines[-10:]}"
        assert g0[0].last_world() == 1
    finally:
        for p in g0 + g1:
            p.kill()


def test_churn_abort_before_ring_no_wedge():
    """Regression: SIGKILL a peer right as the survivors' retry collective
    commences. Members that receive the abort BEFORE entering the ring must
    still retire the op's tag range — otherwise the member that did enter
    waits forever on CMA acks for its staged sends (join_tx wedge; the
    group then never admits the rejoiner). The orchestrated churn bench is
    the repro harness: it must complete all steps with the rejoiner
    admitted, well inside the wedge-detection timeout."""
    from pccl_tpu.comm.native_bench import run_diloco_churn_bench

    # own master port + port band (35xxx-37xxx): this test may run while
    # bench.py exercises the same helper on its default ports
    r = run_diloco_churn_bench(world=4, params_n=2_000_000, n_steps=4,
                               kill_after=1, master_port=48685, base=35000)
    assert r["steps_completed"] == 4, r
    assert r["rejoiner_joined"], r
    assert 3 in r["worlds_seen"] and 4 in r["worlds_seen"], r


# ---------------------------------------------------------------------------
# Straggler-immune data plane (docs/05): mid-collective netem degradation
# with the edge watchdog + live window failover ON vs OFF, same fault map.
# ---------------------------------------------------------------------------

CHAOS_PEER = REPO / "tests" / "chaos_peer.py"


def _run_chaos_world(world: int, count: int, steps: int, fault_at: int,
                     fault: str, watchdog: str, port_base: int,
                     extra_env: dict | None = None):
    """Launch a wire_topology-emulated world of chaos_peer subprocesses and
    return {rank: parsed-json}. The victim (rank 0) injects `fault` on its
    outbound ring edge before step `fault_at` via pccltNetemInject."""
    import json

    from pccl_tpu.comm import MasterNode
    from pccl_tpu.comm.native_bench import wire_topology

    from conftest import alloc_ports

    master = MasterNode("0.0.0.0", alloc_ports())
    master.run()
    procs = []
    try:
        # uniform 300 Mbit emulated mesh: per-endpoint netem edges exist at
        # every peer, so the mid-run injection retunes the LIVE edge
        with wire_topology(world, port_base, mbps=300.0) as envs:
            for r in range(world):
                env = {**envs[r], "PCCLT_WATCHDOG": watchdog,
                       **(extra_env or {})}
                cmd = [sys.executable, str(CHAOS_PEER),
                       "--master-port", str(master.port), "--rank", str(r),
                       "--world", str(world), "--port-base", str(port_base),
                       "--count", str(count), "--steps", str(steps),
                       "--fault-at", str(fault_at), "--fault", fault,
                       "--env", json.dumps(env)]
                procs.append(subprocess.Popen(cmd, stdout=subprocess.PIPE,
                                              stderr=subprocess.STDOUT,
                                              text=True))
            outs = [p.communicate(timeout=420)[0] for p in procs]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        master.interrupt()
        master.destroy()
    results = {}
    for out in outs:
        parsed = None
        for line in out.strip().splitlines():
            try:
                d = json.loads(line)
            except ValueError:
                continue
            if "steps" in d or "error" in d:
                parsed = d
        assert parsed is not None and "error" not in parsed, out[-3000:]
        results[parsed["rank"]] = parsed
    assert set(results) == set(range(world))
    return results


def test_mid_collective_degradation_failover():
    """The ISSUE-10 acceptance scenario: degrade one ring edge 300->10 Mbit
    MID-RUN on a 4-peer world. With the watchdog + failover ON the step
    time recovers to <2x baseline within 3 steps (windows re-issued, then
    relayed around the hop) while the UN-protected run stays >4x degraded
    for the rest of the fault window — same world, same map, same fault.
    No op aborts, no kicks, results bit-identical to the healthy prefix,
    delivered-unique byte conservation exact including relayed + deduped
    windows, and the relayed-window chain balances end to end."""
    from conftest import alloc_ports

    world, count = 4, 1 << 19
    nbytes = count * 4
    fault = "degrade@t=0s:10mbit/300s"  # covers every remaining step

    prot = _run_chaos_world(world, count, steps=9, fault_at=4, fault=fault,
                            watchdog="1", port_base=alloc_ports(span=2300))
    unprot = _run_chaos_world(world, count, steps=9, fault_at=4, fault=fault,
                              watchdog="0", port_base=alloc_ports(span=2300))

    # --- step-time recovery (measure at the victim; steps are collective,
    # so any rank's wall time tracks the world's) ---
    p_steps = prot[0]["steps"]
    base = sorted(p_steps[1:4])[1]  # median healthy step
    post = p_steps[5:9]             # fault hits step 4 (the transition op)
    assert min(p_steps[4:7]) < 2 * base, (base, p_steps)
    assert all(s < 2 * base for s in post[1:]), (base, p_steps)

    u_steps = unprot[0]["steps"]
    u_base = sorted(u_steps[1:4])[1]
    assert all(s > 4 * u_base for s in u_steps[4:7]), (u_base, u_steps)

    # --- bit-identical results: across ranks, AND across the two runs —
    # the same deterministic inputs reduced over the direct path vs the
    # re-issue/relay detours must produce the same bytes (small-integer
    # inputs make the fp32 ring sum exact, so the digest is
    # routing-independent) ---
    digests = {r["digest"] for r in prot.values()} | \
              {r["digest"] for r in unprot.values()}
    assert len(digests) == 1, digests

    # --- no aborts, no kicks, and the failover actually engaged ---
    victims = []
    for r in range(world):
        ctr = prot[r]["stats"]["counters"]
        assert ctr["collectives_aborted"] == 0, (r, ctr)
        assert ctr["collectives_connection_lost"] == 0, (r, ctr)
        assert ctr["kicked"] == 0, (r, ctr)
        for ep, e in prot[r]["stats"]["edges"].items():
            if e["wd_relays"]:
                victims.append((r, ep, e))
    assert len(victims) == 1, victims  # exactly one edge failed over
    _, _, ve = victims[0]
    assert ve["wd_suspects"] >= 1 and ve["wd_confirms"] >= 1, ve
    assert ve["wd_reissues"] >= 1, ve          # rung 1 ran before rung 2
    assert ve["wd_state"] == 2, ve             # CONFIRMED while degraded

    # --- delivered-unique byte conservation, relays + dedupe included:
    # per rank, sum over edges of rx + rx_relay - dup == the ring's exact
    # logical movement for every completed step ---
    expected = 9 * (2 * (world - 1) * nbytes // world)
    for r in range(world):
        edges = prot[r]["stats"]["edges"]
        unique = sum(e["rx_bytes"] + e["rx_relay_bytes"] - e["dup_bytes"]
                     for e in edges.values())
        assert unique == expected, (r, unique, expected, edges)

    # --- the relayed-window chain balances: every window the victim
    # detoured was forwarded by exactly one relay hop and delivered (or
    # deduped) at the destination; duplicate accounting stayed byte-exact
    # rather than window-lossy ---
    relayed = sum(e["wd_relays"] for p in prot.values()
                  for e in p["stats"]["edges"].values())
    forwarded = sum(p["stats"]["counters"]["relay_forwarded"]
                    for p in prot.values())
    received = sum(e["rx_relay_windows"] for p in prot.values()
                   for e in p["stats"]["edges"].values())
    assert relayed == forwarded == received, (relayed, forwarded, received)
    assert sum(e["dup_bytes"] for p in prot.values()
               for e in p["stats"]["edges"].values()) > 0

    # un-protected: no failover machinery may have engaged
    for r in range(world):
        for e in unprot[r]["stats"]["edges"].values():
            assert e["wd_relays"] == 0 and e["rx_relay_bytes"] == 0, (r, e)


@pytest.mark.slow
def test_striped_degradation_failover():
    """ISSUE-15 acceptance: the fault ladder composes with multipath
    striping. Same scripted mid-collective degrade as the ISSUE-10 test,
    but with PCCLT_STRIPE_CONNS=2 — windows ride two pool conns per edge,
    and a stalled stripe re-issues/relays PER STRIPE without dragging the
    healthy one. Recovery inside the hold, zero aborts/kicks, bit-identical
    results, exact delivered-unique conservation across stripes + relays +
    dedupe, and detoured windows striped across >= 2 relay neighbors (the
    PR-10 single-neighbor funnel is gone)."""
    from conftest import alloc_ports

    world, count = 4, 1 << 19
    nbytes = count * 4
    fault = "degrade@t=0s:10mbit/300s"
    env = {"PCCLT_STRIPE_CONNS": "2"}

    prot = _run_chaos_world(world, count, steps=9, fault_at=4, fault=fault,
                            watchdog="1", port_base=alloc_ports(span=2300),
                            extra_env=env)

    # recovery: post-fault steps return under 2x the healthy median
    p_steps = prot[0]["steps"]
    base = sorted(p_steps[1:4])[1]
    assert min(p_steps[4:7]) < 2 * base, (base, p_steps)
    assert all(s < 2 * base for s in p_steps[6:]), (base, p_steps)

    # all ranks agree bit-exactly; zero aborts/kicks anywhere
    assert len({r["digest"] for r in prot.values()}) == 1
    for r in range(world):
        ctr = prot[r]["stats"]["counters"]
        assert ctr["collectives_aborted"] == 0, (r, ctr)
        assert ctr["kicked"] == 0, (r, ctr)

    # striping engaged on every peer's outbound edge, and the ladder ran
    # per stripe on exactly one (the degraded) edge
    striped = sum(e["tx_stripe_windows"] for p in prot.values()
                  for e in p["stats"]["edges"].values())
    assert striped > 0, "striping never engaged"
    victims = [(r, e) for r in range(world)
               for e in prot[r]["stats"]["edges"].values() if e["wd_relays"]]
    assert len(victims) == 1, victims
    assert victims[0][1]["wd_confirms"] >= 1, victims

    # relay fanout: the victim's detours were forwarded by BOTH healthy
    # third peers, not funneled through one (world=4 -> 2 candidates)
    fwd_by_peer = [prot[r]["stats"]["counters"]["relay_forwarded"]
                   for r in range(world)]
    assert sum(1 for f in fwd_by_peer if f > 0) >= 2, fwd_by_peer

    # end-to-end delivery acks flowed back to the origin
    acks = sum(p["stats"]["counters"]["relay_acks"] for p in prot.values())
    assert acks > 0, [p["stats"]["counters"] for p in prot.values()]

    # delivered-unique conservation stays byte-exact with stripes + relays
    expected = 9 * (2 * (world - 1) * nbytes // world)
    for r in range(world):
        edges = prot[r]["stats"]["edges"]
        unique = sum(e["rx_bytes"] + e["rx_relay_bytes"] - e["dup_bytes"]
                     for e in edges.values())
        assert unique == expected, (r, unique, expected, edges)


def test_netem_inject_validation():
    """pccltNetemInject rejects garbage endpoints/specs and accepts the
    documented grammar (degrade/flap/blackhole, ms/s durations, x or the
    Unicode multiplication sign)."""
    from pccl_tpu.comm import PcclError, netem_inject

    netem_inject("127.0.0.1:45991", "degrade@t=0s:40mbit/250ms")
    netem_inject("127.0.0.1:45991", "flap@t=100ms:50msx3;blackhole@t=1s:200ms")
    netem_inject("127.0.0.1:45991", "flap@t=0s:50ms×2")
    netem_inject("127.0.0.1:45991", "")  # disarm
    for bad in ("no-port", "127.0.0.1:0"):
        with pytest.raises(PcclError):
            netem_inject(bad, "blackhole@t=0s:1s")
    with pytest.raises(PcclError):
        netem_inject("127.0.0.1:45991", "meteor@t=0s:1s")
