"""E2E tests for the collective schedule synthesizer + widened collective
vocabulary (docs/12_schedule_synthesis.md).

Covers: reduce_scatter / broadcast / all_to_all bit-exactness against numpy
through the Python API (fp32 with integer-valued payloads, so ring-order
fp32 folds are exact), quantized variants within quantization tolerance,
PCCLT_SCHEDULE_FORCE driving each non-ring algorithm end to end with the
per-algorithm telemetry counters proving which path ran, byte conservation
across the group, and (slow) chaos-map survival with results bit-identical
to an undisturbed ring run.

Real master + N client threads on loopback — never network mocks."""

from __future__ import annotations

import threading
import time
from pathlib import Path

import numpy as np
import pytest

REPO = Path(__file__).resolve().parent.parent
LIB = REPO / "pccl_tpu" / "native" / "build" / "libpcclt.so"
pytestmark = pytest.mark.skipif(not LIB.exists(), reason="native lib not built")

from conftest import alloc_ports  # noqa: E402


def _ports(n=1):
    return alloc_ports(64 * n)


@pytest.fixture
def master():
    from pccl_tpu.comm import MasterNode

    m = MasterNode("0.0.0.0", _ports())
    m.run()
    yield m
    m.interrupt()
    m.destroy()


def _run_peers(master_port, world, worker, base):
    """world client threads; each runs worker(comm, rank)."""
    from pccl_tpu.comm import Communicator

    errors = []

    def peer(rank):
        comm = Communicator("127.0.0.1", master_port,
                            p2p_port=base + rank * 8,
                            ss_port=base + 512 + rank * 8,
                            bench_port=base + 1024 + rank * 8)
        try:
            comm.connect()
            deadline = time.time() + 30
            while comm.world_size < world:
                if time.time() > deadline:
                    raise TimeoutError(f"rank {rank}: world never {world}")
                if comm.are_peers_pending():
                    comm.update_topology()
                time.sleep(0.01)
            worker(comm, rank)
        except Exception as e:  # noqa: BLE001
            errors.append((rank, e))
        finally:
            comm.destroy()

    threads = [threading.Thread(target=peer, args=(r,), daemon=True)
               for r in range(world)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=180)
    hung = [t.name for t in threads if t.is_alive()]
    assert not hung, f"peers still running (wedged?): {hung}"
    assert not errors, f"peer failures: {errors}"


def _slot_data(slot: int, count: int, seed: int = 0) -> np.ndarray:
    """Deterministic integer-valued fp32 payload per slot: group sums stay
    exactly representable, so ring/tree/butterfly fold order is invisible."""
    rng = np.random.default_rng(1000 * seed + slot)
    return rng.integers(0, 512, count).astype(np.float32)


# ---------------------------------------------------------------- broadcast

@pytest.mark.parametrize("world,root", [(2, 0), (3, 0), (4, 3)])
def test_broadcast_bit_exact(master, world, root):
    """Every peer ends bit-identical to the root slot's buffer, non-roots
    starting from poison; count not divisible by world."""
    count = 4099
    barrier = threading.Barrier(world)

    def worker(comm, rank):
        slot = comm.gather_slot
        buf = (_slot_data(root, count) if slot == root
               else np.full(count, -7.0, dtype=np.float32))
        info = comm.broadcast(buf, root=root, tag=5)
        assert info.world_size == world
        assert np.array_equal(buf, _slot_data(root, count))
        barrier.wait(timeout=60)

    _run_peers(master.port, world, worker, _ports(6))


def test_broadcast_solo(master):
    def worker(comm, rank):
        buf = np.arange(5, dtype=np.float32)
        info = comm.broadcast(buf, root=0)
        assert info.world_size == 1
        assert np.array_equal(buf, np.arange(5, dtype=np.float32))

    _run_peers(master.port, 1, worker, _ports(4))


# ----------------------------------------------------------- reduce-scatter

@pytest.mark.parametrize("world", [2, 3, 4])
def test_reduce_scatter_bit_exact(master, world):
    """Each peer's chunk equals the numpy group sum at [offset, offset+n);
    the chunks tile the full vector exactly once; tx == rx group-wide."""
    count = 2053
    total = np.sum([_slot_data(s, count, seed=2) for s in range(world)],
                   axis=0, dtype=np.float32)
    results = {}
    infos = {}
    lock = threading.Lock()

    def worker(comm, rank):
        slot = comm.gather_slot
        chunk, off, info = comm.reduce_scatter(
            _slot_data(slot, count, seed=2), tag=6)
        assert info.world_size == world
        assert np.array_equal(chunk, total[off:off + chunk.size])
        with lock:
            results[rank] = (off, chunk.size)
            infos[rank] = info

    _run_peers(master.port, world, worker, _ports(6))
    # the chunks tile [0, count) exactly (no gap, no overlap)
    spans = sorted(results.values())
    assert spans[0][0] == 0
    assert sum(n for _, n in spans) == count
    for (o1, n1), (o2, _) in zip(spans, spans[1:]):
        assert o1 + n1 == o2, spans
    # conservation: every byte sent was received exactly once
    assert sum(i.tx_bytes for i in infos.values()) == \
        sum(i.rx_bytes for i in infos.values())


def test_reduce_scatter_quantized(master):
    """Min-max quantized wire format: within quantization tolerance."""
    from pccl_tpu.comm import QuantizationAlgorithm

    world, count = 3, 1024
    total = np.sum([_slot_data(s, count, seed=3) for s in range(world)],
                   axis=0, dtype=np.float32)

    def worker(comm, rank):
        slot = comm.gather_slot
        chunk, off, info = comm.reduce_scatter(
            _slot_data(slot, count, seed=3), tag=7,
            quantization=QuantizationAlgorithm.MIN_MAX)
        assert info.world_size == world
        np.testing.assert_allclose(chunk, total[off:off + chunk.size],
                                   atol=1.5 * world * 2.0)

    _run_peers(master.port, world, worker, _ports(6))


# --------------------------------------------------------------- all-to-all

@pytest.mark.parametrize("world", [2, 3, 4])
def test_all_to_all_bit_exact(master, world):
    """recv block i must be exactly the block peer (slot i) addressed to
    this peer's slot: recv_j[i] == send_i[j] group-wide, bit-for-bit."""
    per = 193
    lock = threading.Lock()
    infos = {}

    def worker(comm, rank):
        slot = comm.gather_slot
        send = np.concatenate(
            [_slot_data(slot * world + j, per, seed=4)
             for j in range(world)])
        recv, info = comm.all_to_all(send, tag=8)
        assert info.world_size == world
        for i in range(world):
            expect = _slot_data(i * world + slot, per, seed=4)
            assert np.array_equal(recv[i * per:(i + 1) * per], expect), \
                f"slot {slot}: block from {i} wrong"
        with lock:
            infos[rank] = info

    _run_peers(master.port, world, worker, _ports(6))
    assert sum(i.tx_bytes for i in infos.values()) == \
        sum(i.rx_bytes for i in infos.values())


def test_all_to_all_quantized(master):
    from pccl_tpu.comm import QuantizationAlgorithm

    world, per = 3, 256

    def worker(comm, rank):
        slot = comm.gather_slot
        send = np.concatenate(
            [_slot_data(slot * world + j, per, seed=5)
             for j in range(world)])
        recv, info = comm.all_to_all(
            send, tag=9, quantization=QuantizationAlgorithm.MIN_MAX)
        assert info.world_size == world
        for i in range(world):
            expect = _slot_data(i * world + slot, per, seed=5)
            np.testing.assert_allclose(recv[i * per:(i + 1) * per], expect,
                                       atol=4.0)

    _run_peers(master.port, world, worker, _ports(6))


# ------------------------------------------------- forced non-ring programs

def _sched_counters(comm):
    c = comm.stats()["counters"]
    return {k: v for k, v in c.items() if k.startswith("sched_")}


def test_forced_tree_broadcast_matches_ring(master, monkeypatch):
    """PCCLT_SCHEDULE_FORCE=tree: the star program delivers the identical
    bytes the ring chain would, and sched_ops_tree proves the tree ran."""
    monkeypatch.setenv("PCCLT_SCHEDULE_FORCE", "tree")
    world, count = 3, 8191
    lock = threading.Lock()
    counters = {}

    def worker(comm, rank):
        slot = comm.gather_slot
        buf = (_slot_data(1, count, seed=6) if slot == 1
               else np.zeros(count, dtype=np.float32))
        comm.broadcast(buf, root=1, tag=10)
        assert np.array_equal(buf, _slot_data(1, count, seed=6))
        with lock:
            counters[rank] = _sched_counters(comm)

    _run_peers(master.port, world, worker, _ports(6))
    assert sum(c["sched_ops_tree"] for c in counters.values()) == world
    assert all(c["sched_steps"] > 0 for c in counters.values()), counters


def test_forced_butterfly_allreduce_exact(master, monkeypatch):
    """PCCLT_SCHEDULE_FORCE=butterfly on a power-of-two world: the
    halving/doubling program sums exactly (integer-valued fp32) and the
    butterfly counter proves the stamped algorithm actually executed."""
    monkeypatch.setenv("PCCLT_SCHEDULE_FORCE", "butterfly")
    world, count = 4, 4099
    total = np.sum([_slot_data(s, count, seed=7) for s in range(world)],
                   axis=0, dtype=np.float32)
    lock = threading.Lock()
    counters = {}

    def worker(comm, rank):
        slot = comm.gather_slot
        buf = _slot_data(slot, count, seed=7).copy()
        comm.all_reduce(buf, tag=11)
        assert np.array_equal(buf, total)
        with lock:
            counters[rank] = _sched_counters(comm)

    _run_peers(master.port, world, worker, _ports(6))
    assert sum(c["sched_ops_butterfly"] for c in counters.values()) == world


def test_forced_mesh_all_to_all(master, monkeypatch):
    """PCCLT_SCHEDULE_FORCE=mesh: direct pairwise exchange, same bytes."""
    monkeypatch.setenv("PCCLT_SCHEDULE_FORCE", "mesh")
    world, per = 3, 128
    lock = threading.Lock()
    counters = {}

    def worker(comm, rank):
        slot = comm.gather_slot
        send = np.concatenate(
            [_slot_data(slot * world + j, per, seed=8)
             for j in range(world)])
        recv, _ = comm.all_to_all(send, tag=12)
        for i in range(world):
            assert np.array_equal(
                recv[i * per:(i + 1) * per],
                _slot_data(i * world + slot, per, seed=8))
        with lock:
            counters[rank] = _sched_counters(comm)

    _run_peers(master.port, world, worker, _ports(6))
    assert sum(c["sched_ops_mesh"] for c in counters.values()) == world


def test_schedule_off_pins_ring(master, monkeypatch):
    """PCCLT_SCHEDULE=0 ignores any table/force: only the ring counter
    moves (kill switch, docs/12)."""
    monkeypatch.setenv("PCCLT_SCHEDULE", "0")
    monkeypatch.setenv("PCCLT_SCHEDULE_FORCE", "tree")
    world, count = 2, 1024
    lock = threading.Lock()
    counters = {}

    def worker(comm, rank):
        slot = comm.gather_slot
        buf = (_slot_data(0, count, seed=9) if slot == 0
               else np.zeros(count, dtype=np.float32))
        comm.broadcast(buf, root=0, tag=13)
        assert np.array_equal(buf, _slot_data(0, count, seed=9))
        with lock:
            counters[rank] = _sched_counters(comm)

    _run_peers(master.port, world, worker, _ports(6))
    assert sum(c["sched_ops_tree"] for c in counters.values()) == 0
    assert sum(c["sched_ops_ring"] for c in counters.values()) == world


# -------------------------------------------------------- chaos + degrade

@pytest.mark.slow
def test_tree_broadcast_survives_chaos_map(master, monkeypatch):
    """Acceptance: a PCCLT_WIRE_CHAOS_MAP armed on a tree (non-ring) edge
    — flap + degrade — must not abort or kick anyone, and every peer's
    result stays bit-identical to the root's buffer (which IS the ring
    result: broadcast is algorithm-invariant)."""
    world, count = 4, 1 << 18
    base = _ports(8)
    # chaos on every peer's p2p endpoint: whichever edges the tree dials
    # (root fan-out is not knowable up front — slot->rank mapping is the
    # master's) are guaranteed covered, including never-ringed ones
    eps = [f"127.0.0.1:{base + r * 8}" for r in range(world)]
    chaos = ",".join(f"{ep}=degrade@t=0s:80mbit/3s;flap@t=1s:60msx3"
                     for ep in eps)
    monkeypatch.setenv("PCCLT_WIRE_CHAOS_MAP", chaos)
    monkeypatch.setenv("PCCLT_WIRE_MBPS", "800")
    monkeypatch.setenv("PCCLT_SCHEDULE_FORCE", "tree")
    monkeypatch.setenv("PCCLT_WATCHDOG", "1")
    lock = threading.Lock()
    counters = {}

    def worker(comm, rank):
        slot = comm.gather_slot
        for it in range(3):
            buf = (_slot_data(2, count, seed=20 + it) if slot == 2
                   else np.zeros(count, dtype=np.float32))
            comm.broadcast(buf, root=2, tag=14 + it)
            assert np.array_equal(buf, _slot_data(2, count, seed=20 + it)), \
                f"iteration {it} diverged under chaos"
        with lock:
            counters[rank] = comm.stats()["counters"]

    _run_peers(master.port, world, worker, base)
    assert sum(c["collectives_aborted"] for c in counters.values()) == 0, \
        counters
    assert sum(c["sched_ops_tree"] for c in counters.values()) == 3 * world


@pytest.mark.slow
def test_butterfly_survives_mid_collective_degrade(master, monkeypatch):
    """Mid-collective netem degrade on a butterfly exchange partner with
    the watchdog armed: the op completes exactly (integer-valued fp32),
    nobody is kicked, and later iterations keep succeeding."""
    from pccl_tpu.comm import netem_inject

    world, count = 4, 1 << 18
    base = _ports(8)
    monkeypatch.setenv("PCCLT_WIRE_MBPS", "600")
    monkeypatch.setenv("PCCLT_SCHEDULE_FORCE", "butterfly")
    monkeypatch.setenv("PCCLT_WATCHDOG", "1")
    total = {it: np.sum([_slot_data(s, count, seed=30 + it)
                         for s in range(world)], axis=0, dtype=np.float32)
             for it in range(3)}
    lock = threading.Lock()
    counters = {}

    def worker(comm, rank):
        slot = comm.gather_slot
        for it in range(3):
            if it == 1 and rank == 0:
                # degrade OUR busiest live edge mid-run (slot->endpoint
                # mapping is discovered from stats, like chaos_peer.py)
                edges = comm.stats()["edges"]
                if edges:
                    victim = max(edges.items(),
                                 key=lambda kv: kv[1]["tx_bytes"])[0]
                    netem_inject(victim, "degrade@t=0s:40mbit/4s")
            buf = _slot_data(slot, count, seed=30 + it).copy()
            comm.all_reduce(buf, tag=17 + it)
            assert np.array_equal(buf, total[it]), f"iteration {it} wrong"
        with lock:
            counters[rank] = comm.stats()["counters"]

    _run_peers(master.port, world, worker, base)
    assert sum(c["collectives_aborted"] for c in counters.values()) == 0
    assert sum(c["sched_ops_butterfly"] for c in counters.values()) == \
        3 * world


@pytest.mark.slow
@pytest.mark.parametrize("world", [8])
def test_new_collectives_world8(master, world):
    """The full widened vocabulary at world 8 (butterfly-eligible), one
    pass each, bit-exact."""
    count = 4096 + 56  # divisible by 8 for a2a blocks after // world
    per = count // world
    total = np.sum([_slot_data(s, count, seed=40) for s in range(world)],
                   axis=0, dtype=np.float32)

    def worker(comm, rank):
        slot = comm.gather_slot
        buf = (_slot_data(0, count, seed=41) if slot == 0
               else np.zeros(count, dtype=np.float32))
        comm.broadcast(buf, root=0, tag=30)
        assert np.array_equal(buf, _slot_data(0, count, seed=41))

        chunk, off, info = comm.reduce_scatter(
            _slot_data(slot, count, seed=40), tag=31)
        assert np.array_equal(chunk, total[off:off + chunk.size])

        send = np.concatenate([_slot_data(slot * world + j, per, seed=42)
                               for j in range(world)])
        recv, _ = comm.all_to_all(send, tag=32)
        for i in range(world):
            assert np.array_equal(
                recv[i * per:(i + 1) * per],
                _slot_data(i * world + slot, per, seed=42))

    _run_peers(master.port, world, worker, _ports(10))
