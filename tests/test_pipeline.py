"""Pipeline parallelism: pipelined forward/backward match the dense model."""

from __future__ import annotations

import numpy as np
import pytest


def _setup(eight_devices, n_stages, n_layer=4, B=4, T=32):
    import jax

    from pccl_tpu.models import gpt
    from pccl_tpu.parallel import mesh as mesh_lib
    from pccl_tpu.parallel import pipeline

    mesh = mesh_lib.make_mesh(eight_devices[:n_stages], ("pp",), (n_stages,))
    cfg = gpt.tiny_config(n_layer=n_layer, n_head=2, n_embd=32, block_size=T,
                          vocab_size=128)
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    params = {**params,
              **pipeline.shard_layer_params(
                  {k: params[k] for k in gpt._LAYER_KEYS}, mesh)}
    tokens = jax.random.randint(jax.random.PRNGKey(1), (B, T), 0,
                                cfg.vocab_size)
    return mesh, cfg, params, tokens


@pytest.mark.parametrize("n_stages,microbatches", [(2, 2), (4, 4), (4, 2)])
def test_pipelined_forward_matches_dense(eight_devices, n_stages, microbatches):
    import jax

    from pccl_tpu.models import gpt
    from pccl_tpu.parallel import pipeline

    mesh, cfg, params, tokens = _setup(eight_devices, n_stages)
    dense = gpt.forward(params, tokens, cfg)
    fwd = pipeline.build_pipelined_forward(cfg, mesh,
                                           microbatches=microbatches)
    piped = jax.jit(fwd)(params, tokens)
    np.testing.assert_allclose(np.asarray(piped), np.asarray(dense),
                               rtol=2e-2, atol=2e-2)  # bf16 compute


def test_pipelined_backward_matches_dense(eight_devices):
    import jax
    import jax.numpy as jnp

    from pccl_tpu.models import gpt
    from pccl_tpu.parallel import pipeline

    mesh, cfg, params, tokens = _setup(eight_devices, 2, B=2, T=16)
    targets = tokens

    def loss_dense(p):
        return gpt.loss_fn(p, tokens, targets, cfg)

    fwd = pipeline.build_pipelined_forward(cfg, mesh)

    def loss_piped(p):
        logits = fwd(p, tokens)
        logp = jax.nn.log_softmax(logits, axis=-1)
        ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
        return -jnp.mean(ll)

    g_dense = jax.grad(loss_dense)(params)
    g_piped = jax.jit(jax.grad(loss_piped))(params)
    for k in g_dense:
        np.testing.assert_allclose(np.asarray(g_piped[k]),
                                   np.asarray(g_dense[k]),
                                   rtol=5e-2, atol=5e-2,
                                   err_msg=f"grad mismatch for {k}")
