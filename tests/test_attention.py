"""Ring attention and flash attention parity vs the dense reference."""

import numpy as np


def _qkv(B=2, T=64, H=4, Dh=16, seed=0):
    import jax

    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    shape = (B, T, H, Dh)
    import jax.numpy as jnp

    q = jax.random.normal(ks[0], shape, jnp.float32)
    k = jax.random.normal(ks[1], shape, jnp.float32)
    v = jax.random.normal(ks[2], shape, jnp.float32)
    return q, k, v


def test_flash_interpret_matches_reference():
    from pccl_tpu.ops import flash_attention, reference_attention

    q, k, v = _qkv(T=128)
    ref = reference_attention(q, k, v)
    out = flash_attention(q, k, v, block_q=32, block_k=32, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_noncausal_interpret():
    from pccl_tpu.ops import flash_attention, reference_attention

    q, k, v = _qkv(T=64)
    ref = reference_attention(q, k, v, causal=False)
    out = flash_attention(q, k, v, causal=False, block_q=32, block_k=32,
                          interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_grad_matches_reference():
    """flash_attention must be differentiable (training-path attn_fn)."""
    import jax
    import jax.numpy as jnp

    from pccl_tpu.ops import flash_attention, reference_attention

    q, k, v = _qkv(T=64)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, block_q=32, block_k=32,
                                       interpret=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v) ** 2)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr in zip(g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   rtol=1e-4, atol=1e-4)


def test_ring_attention_matches_dense(eight_devices):
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from pccl_tpu.ops import reference_attention, ring_attention
    from pccl_tpu.parallel import mesh as mesh_lib

    mesh = mesh_lib.make_mesh(eight_devices, axis_names=("dp", "sp"),
                              shape=(2, 4))
    q, k, v = _qkv(B=4, T=64, H=4, Dh=16)
    ref = reference_attention(q, k, v)
    sh = NamedSharding(mesh, P("dp", "sp"))
    qs, ks, vs = (jax.device_put(x, sh) for x in (q, k, v))
    out = jax.jit(lambda a, b, c: ring_attention(a, b, c, mesh))(qs, ks, vs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_attention_grad_flows(eight_devices):
    """Ring attention must be differentiable (training path)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from pccl_tpu.ops import reference_attention, ring_attention
    from pccl_tpu.parallel import mesh as mesh_lib

    mesh = mesh_lib.make_mesh(eight_devices[:4], axis_names=("sp",), shape=(4,))
    q, k, v = _qkv(B=2, T=32, H=2, Dh=8)

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention(q, k, v, mesh, batch_axis=None) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v) ** 2)

    g_ring = jax.jit(jax.grad(loss_ring))(q, k, v)
    g_ref = jax.grad(loss_ref)(q, k, v)
    np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_ref),
                               rtol=1e-4, atol=1e-4)


def test_gpt_forward_with_ring_attention(eight_devices):
    """Full model forward under sequence parallelism matches dense."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from pccl_tpu.models import gpt
    from pccl_tpu.ops.ring_attention import make_ring_attn_fn
    from pccl_tpu.parallel import mesh as mesh_lib

    mesh = mesh_lib.make_mesh(eight_devices[:4], axis_names=("sp",), shape=(4,))
    cfg = gpt.tiny_config(block_size=64)
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 64), 0, cfg.vocab_size)

    dense = gpt.forward(params, tokens, cfg)
    tok_sp = jax.device_put(tokens, NamedSharding(mesh, P(None, "sp")))
    ringed = jax.jit(lambda p, t: gpt.forward(
        p, t, cfg, attn_fn=make_ring_attn_fn(mesh, batch_axis=None)))(params, tok_sp)
    np.testing.assert_allclose(np.asarray(ringed), np.asarray(dense),
                               rtol=2e-2, atol=2e-2)  # bf16 compute


def test_llama_forward_with_ring_attention(eight_devices):
    """Llama's GQA must compose with the attn_fn override: kv heads are
    repeated to the full head count on device BEFORE the attention op
    (models/llama.py:_block), so ring attention sees ordinary multi-head
    inputs and sequence parallelism works unchanged for the second family."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from pccl_tpu.models import llama
    from pccl_tpu.ops.ring_attention import make_ring_attn_fn
    from pccl_tpu.parallel import mesh as mesh_lib

    import jax.numpy as jnp

    mesh = mesh_lib.make_mesh(eight_devices[:4], axis_names=("sp",), shape=(4,))
    # fp32 compute: the test checks GQA/ring COMPOSITION, and SwiGLU's
    # multiplicative gating amplifies bf16 attention rounding past any
    # meaningful tolerance (observed 0.05 on logits for an exact ring)
    cfg = llama.tiny_config(block_size=64, compute_dtype=jnp.float32)
    assert cfg.n_kv_head != cfg.n_head   # n_kv_head=2 < n_head=4: real GQA
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 64), 0,
                                cfg.vocab_size)

    dense = llama.forward(params, tokens, cfg)
    tok_sp = jax.device_put(tokens, NamedSharding(mesh, P(None, "sp")))
    ringed = jax.jit(lambda p, t: llama.forward(
        p, t, cfg, attn_fn=make_ring_attn_fn(mesh, batch_axis=None)))(
            params, tok_sp)
    np.testing.assert_allclose(np.asarray(ringed), np.asarray(dense),
                               rtol=1e-4, atol=1e-4)


def test_flash_grad_noncausal_and_asym_blocks():
    """Regression cover for the fused backward's untested corners: the
    non-causal branch and block_q != block_k (exercises the dkv kernel's
    diagonal start-block arithmetic j0 = ki*block_k // block_q)."""
    import jax
    import jax.numpy as jnp

    from pccl_tpu.ops.flash_attention import _flash_diff, reference_attention

    q, k, v = _qkv(B=1, T=128, H=2, Dh=16)

    for causal, bq, bk in ((False, 32, 32), (True, 16, 64), (True, 64, 16)):
        def loss_f(q, k, v):
            return jnp.sum(_flash_diff(q, k, v, causal, bq, bk, True) ** 2)

        def loss_r(q, k, v):
            return jnp.sum(reference_attention(q, k, v, causal=causal) ** 2)

        gf = jax.grad(loss_f, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_r, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-4), (causal, bq, bk)


def test_flash_with_lse_pair_grads():
    """flash_attention_with_lse returns a DIFFERENTIABLE (out, lse) pair —
    the form ring attention folds per shard. The backward folds the lse
    cotangent into delta (ds = p*(dp - (delta - dlse))), so a loss that
    touches BOTH outputs must match the jnp twin exactly."""
    import jax
    import jax.numpy as jnp

    from pccl_tpu.ops.flash_attention import (dense_attention_with_lse,
                                              flash_attention_with_lse)

    q, k, v = _qkv(B=2, T=64, H=2, Dh=16)

    for causal in (True, False):
        of, lf = flash_attention_with_lse(q, k, v, causal, 32, 32, True)
        od, ld = dense_attention_with_lse(q, k, v, causal)
        np.testing.assert_allclose(np.asarray(of), np.asarray(od),
                                   rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(np.asarray(lf), np.asarray(ld),
                                   rtol=1e-5, atol=1e-5)

        def loss_f(q, k, v):
            o, l = flash_attention_with_lse(q, k, v, causal, 32, 32, True)
            return jnp.sum(o ** 2) + jnp.sum(jnp.sin(l))  # both outputs live

        def loss_d(q, k, v):
            o, l = dense_attention_with_lse(q, k, v, causal)
            return jnp.sum(o ** 2) + jnp.sum(jnp.sin(l))

        gf = jax.grad(loss_f, argnums=(0, 1, 2))(q, k, v)
        gd = jax.grad(loss_d, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gd):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-4)


def _gqa_qkv(B=2, T=128, H=8, Hkv=2, Dh=16, seed=3):
    import jax
    import jax.numpy as jnp

    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (B, T, H, Dh), jnp.float32)
    k = jax.random.normal(ks[1], (B, T, Hkv, Dh), jnp.float32)
    v = jax.random.normal(ks[2], (B, T, Hkv, Dh), jnp.float32)
    return q, k, v


def test_flash_gqa_matches_repeated_dense():
    """GQA-native kernels (Hkv-shaped K/V, head mapping in the BlockSpec
    index maps — no jnp.repeat anywhere on the kernel path) must match
    dense attention over explicitly repeated K/V, forward and backward.
    VERDICT r4 ask #2: llama's K/V repeat erased the architecture's
    KV-bytes advantage."""
    import jax
    import jax.numpy as jnp

    from pccl_tpu.ops.flash_attention import _flash_diff, reference_attention

    q, k, v = _gqa_qkv()
    G = q.shape[2] // k.shape[2]
    krep = jnp.repeat(k, G, axis=2)
    vrep = jnp.repeat(v, G, axis=2)

    for causal in (True, False):
        out = _flash_diff(q, k, v, causal, 32, 32, True)
        ref = reference_attention(q, krep, vrep, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def loss_f(q, k, v):
        return jnp.sum(_flash_diff(q, k, v, True, 32, 32, True) ** 2)

    def loss_r(q, k, v):
        out = reference_attention(q, jnp.repeat(k, G, axis=2),
                                  jnp.repeat(v, G, axis=2))
        return jnp.sum(out ** 2)

    # autodiff through loss_r's jnp.repeat already folds the G copies, so
    # both sides produce the native Hkv-shaped dk/dv
    gf = jax.grad(loss_f, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_r, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        assert a.shape == b.shape
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_flash_gqa_with_lse_pair():
    """The (out, lse) pair path (ring attention's per-shard form) with
    GQA-shaped K/V: values and both-output grads match the jnp twin."""
    import jax
    import jax.numpy as jnp

    from pccl_tpu.ops.flash_attention import (dense_attention_with_lse,
                                              flash_attention_with_lse)

    q, k, v = _gqa_qkv(B=1, T=64, H=4, Hkv=2)

    of, lf = flash_attention_with_lse(q, k, v, True, 32, 32, True)
    od, ld = dense_attention_with_lse(q, k, v, True)
    np.testing.assert_allclose(np.asarray(of), np.asarray(od),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(lf), np.asarray(ld),
                               rtol=1e-5, atol=1e-5)

    def loss_f(q, k, v):
        o, l = flash_attention_with_lse(q, k, v, True, 32, 32, True)
        return jnp.sum(o ** 2) + jnp.sum(jnp.sin(l))

    def loss_d(q, k, v):
        o, l = dense_attention_with_lse(q, k, v, True)
        return jnp.sum(o ** 2) + jnp.sum(jnp.sin(l))

    gf = jax.grad(loss_f, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_d, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        assert a.shape == b.shape
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)
