"""Subprocess peer for shared-state chunk-plane scenario tests (docs/04).

One OS process per peer so a scenario can SIGKILL a seeder mid-sync — the
acceptance gate of the churn-proof chunk plane is that the round completes
bit-identically for every survivor with zero aborts.

Roles:
  seeder  — offers the popular content (deterministic rng) at --revision
  joiner  — offers zeros at revision 0, adopts the popular content

``--suicide-after-served N`` arms a watcher thread that SIGKILLs THIS
process the moment its own ``ss_seeder_chunks_served`` counter reaches N:
a deterministic "the busiest seeder dies mid-serve", no orchestrator
timing games. Results are written as JSON to --result-file (absent for
the killed peer, by design).

``--inject-on-serve ENDPOINT=SPEC`` arms a watcher that calls
``netem_inject(ENDPOINT, SPEC)`` the moment this peer's own per-edge
``tx_sync_bytes`` toward ENDPOINT goes nonzero. Serve accounting is
counted BEFORE the striped sends launch, so the injected fault lands
while the serve's paced window is still in flight — a deterministic
"the seeder's egress edge dies mid-serve" (the watchdog-ladder gate).

``--linger-s S`` sleeps S seconds between the sync returning and the
stats snapshot, so cross-peer aftermath (relay detours, acks) lands in
the recorded counters.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import signal
import sys
import threading
import time

import numpy as np


def content_arrays(keys: int, elems: int, popular: bool) -> dict:
    if popular:
        rng = np.random.default_rng(20260804)
        return {f"k{i}": rng.standard_normal(elems).astype(np.float32)
                for i in range(keys)}
    return {f"k{i}": np.zeros(elems, dtype=np.float32) for i in range(keys)}


def digest_of(arrays: dict) -> str:
    h = hashlib.sha256()
    for k in sorted(arrays):
        h.update(k.encode())
        h.update(arrays[k].tobytes())
    return h.hexdigest()


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--master-port", type=int, required=True)
    ap.add_argument("--world", type=int, required=True)
    ap.add_argument("--rank", type=int, required=True)
    ap.add_argument("--role", choices=["seeder", "joiner"], required=True)
    ap.add_argument("--keys", type=int, default=8)
    ap.add_argument("--elems", type=int, default=65536)
    ap.add_argument("--revision", type=int, default=1)
    ap.add_argument("--suicide-after-served", type=int, default=0)
    ap.add_argument("--inject-on-serve", default="")
    ap.add_argument("--linger-s", type=float, default=0.0)
    ap.add_argument("--p2p-port", type=int, default=0)
    ap.add_argument("--ss-port", type=int, default=0)
    ap.add_argument("--bench-port", type=int, default=0)
    ap.add_argument("--result-file", required=True)
    args = ap.parse_args()

    from pccl_tpu.comm import (Communicator, SharedState,
                               SharedStateSyncStrategy, TensorInfo,
                               netem_inject)

    comm = Communicator("127.0.0.1", args.master_port,
                        p2p_port=args.p2p_port, ss_port=args.ss_port,
                        bench_port=args.bench_port)
    comm.connect()
    deadline = time.time() + 60
    while comm.global_world_size < args.world:
        if time.time() > deadline:
            print(f"rank {args.rank}: world never formed", file=sys.stderr)
            return 2
        if comm.are_peers_pending():
            comm.update_topology()
        time.sleep(0.01)

    if args.suicide_after_served > 0:
        def watcher():
            while True:
                served = comm.stats()["counters"]["ss_seeder_chunks_served"]
                if served >= args.suicide_after_served:
                    # mid-serve by construction: this peer IS actively
                    # seeding the in-flight round when it dies
                    os.kill(os.getpid(), signal.SIGKILL)
                time.sleep(0.002)
        threading.Thread(target=watcher, daemon=True).start()

    if args.inject_on_serve:
        endpoint, spec = args.inject_on_serve.split("=", 1)

        def injector():
            while True:
                e = comm.stats()["edges"].get(endpoint)
                if e and e["tx_sync_bytes"] > 0:
                    # the serve toward `endpoint` is counted pre-send: its
                    # paced window is in flight RIGHT NOW — arm the fault
                    netem_inject(endpoint, spec)
                    return
                time.sleep(0.001)
        threading.Thread(target=injector, daemon=True).start()

    arrays = content_arrays(args.keys, args.elems, args.role == "seeder")
    rev = args.revision if args.role == "seeder" else 0
    st = SharedState([TensorInfo.from_numpy(k, v) for k, v in arrays.items()],
                     revision=rev)
    t0 = time.perf_counter()
    info = comm.sync_shared_state(st, SharedStateSyncStrategy.ENFORCE_POPULAR)
    wall = time.perf_counter() - t0

    if args.linger_s > 0:
        # keep the mesh up so in-flight aftermath (watchdog relay detours,
        # delivery acks) lands in the snapshot below
        time.sleep(args.linger_s)
    stats = comm.stats()
    res = {
        "rank": args.rank,
        "role": args.role,
        "revision": info.revision,
        "tx_bytes": info.tx_bytes,
        "rx_bytes": info.rx_bytes,
        "sync_wall_s": wall,
        "digest": digest_of(arrays),
        "counters": stats["counters"],
        "edges": stats["edges"],
    }
    with open(args.result_file, "w") as f:
        json.dump(res, f)
    comm.destroy()
    return 0


if __name__ == "__main__":
    sys.exit(main())
