"""Flight-recorder telemetry: per-edge byte conservation, merged Chrome
trace round-trip, stats across a peer kick, and the profiler guards.

Reference parity: the reference has no counterpart — its only native
visibility is stderr timing lines. This subsystem exists because the WAN
training loop (Prime's report, arxiv 2505.14065) needs to answer "was the
step slow because of the wire, a straggler peer, or quantization?" with
data; arxiv 2606.01680 makes per-edge visibility the prerequisite for
every AllReduce robustness claim.
"""

from __future__ import annotations

import json
import subprocess
import sys
import threading
import time
from pathlib import Path

import numpy as np
import pytest

REPO = Path(__file__).resolve().parent.parent
LIB = REPO / "pccl_tpu" / "native" / "build" / "libpcclt.so"
pytestmark = pytest.mark.skipif(not LIB.exists(), reason="native lib not built")

from conftest import alloc_ports


@pytest.mark.parametrize("plane", [
    # the fallback matrix (docs/08 ladder): the windowed pipeline + io_uring
    # backend forced ON, and forced OFF (uring unavailable → poll loop +
    # un-windowed stages). Byte conservation must hold EXACTLY on both.
    pytest.param({"PCCLT_PIPELINE": "1", "PCCLT_URING": "1"}, id="pipelined"),
    pytest.param({"PCCLT_PIPELINE": "0", "PCCLT_URING": "0"}, id="poll-loop"),
])
def test_edge_conservation_and_merged_trace(tmp_path, plane):
    """The acceptance scenario: a wire_topology-emulated 4-peer all-reduce.

    Per-edge counters must conserve bytes exactly:
      * each peer's total data tx across edges == 2*(n-1)/n * payload
        (the ring's logical movement; count divisible by n, unquantized,
        so equality is exact);
      * peer i's tx toward its successor == the successor's rx keyed by
        i's canonical endpoint (both sides count the same frames).
    And rank 0's MERGED Chrome trace (Python profiler sections + native
    recorder events) must parse with both tracks present."""
    from pccl_tpu.comm import MasterNode
    from pccl_tpu.comm.native_bench import _rank_ports, wire_topology

    world, count = 4, 1 << 18  # 1 MiB payload, divisible by 4
    port_base = alloc_ports(span=2300)
    master = MasterNode("0.0.0.0", alloc_ports())
    master.run()
    trace_path = tmp_path / "merged_trace.json"
    procs = []
    try:
        # uniform emulated mesh: forces every byte onto the streamed TCP
        # path (emulation disables the same-host zero-copy transports), so
        # the counters meter real wire frames
        with wire_topology(world, port_base, mbps=4000.0) as envs:
            for r in range(world):
                cmd = [sys.executable, str(REPO / "tests" / "telemetry_peer.py"),
                       "--master-port", str(master.port), "--rank", str(r),
                       "--world", str(world), "--port-base", str(port_base),
                       "--count", str(count),
                       "--env", json.dumps({**envs[r], **plane})]
                if r == 0:
                    cmd += ["--trace-out", str(trace_path)]
                procs.append(subprocess.Popen(cmd, stdout=subprocess.PIPE,
                                              stderr=subprocess.STDOUT,
                                              text=True))
            outs = [p.communicate(timeout=180)[0] for p in procs]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        master.interrupt()
        master.destroy()
    stats = {}
    for out in outs:
        line = out.strip().splitlines()[-1]
        r = json.loads(line)
        assert "error" not in r, out[-2000:]
        stats[r["rank"]] = r["stats"]
    assert set(stats) == set(range(world))

    nbytes = count * 4
    expected = 2 * (world - 1) * nbytes // world
    endpoint_of = {r: f"127.0.0.1:{_rank_ports(port_base, r)[0]}"
                   for r in range(world)}
    rank_of = {ep: r for r, ep in endpoint_of.items()}
    for r in range(world):
        edges = stats[r]["edges"]
        tx_total = sum(e["tx_bytes"] for e in edges.values())
        rx_total = sum(e["rx_bytes"] for e in edges.values())
        assert tx_total == expected, \
            f"rank {r}: tx {tx_total} != {expected} ({edges})"
        assert rx_total == expected, f"rank {r}: rx {rx_total} != {expected}"
        # exactly one successor edge carries the tx
        tx_edges = {ep: e for ep, e in edges.items() if e["tx_bytes"]}
        assert len(tx_edges) == 1, f"rank {r}: tx spread over {tx_edges}"
        (succ_ep, e), = tx_edges.items()
        succ = rank_of[succ_ep]
        # the successor's rx from OUR endpoint matches our tx bitwise
        succ_rx = stats[succ]["edges"][endpoint_of[r]]
        assert succ_rx["rx_bytes"] == e["tx_bytes"], \
            f"edge {r}->{succ}: tx {e['tx_bytes']} != rx {succ_rx['rx_bytes']}"
        assert succ_rx["rx_frames"] == e["tx_frames"]
        assert e["connects"] >= 1

    # merged trace: valid JSON, python + native tracks, spans well-formed
    trace = json.loads(trace_path.read_text())
    events = trace["traceEvents"]
    assert isinstance(events, list) and events
    names = {e.get("name") for e in events}
    assert "py/all_reduce" in names          # python profiler track
    assert "allreduce" in names              # native collective span
    assert "reduce_scatter" in names and "all_gather" in names
    assert "wire_stall" in names
    pids = {e.get("pid") for e in events}
    assert 0 in pids and len(pids) >= 2      # separate process tracks
    for e in events:
        assert "name" in e and "ph" in e
        if e["ph"] in ("X", "i"):
            assert e["ts"] >= 0
        if e["ph"] == "X":
            assert e["dur"] >= 0
    # the python section must overlap the native allreduce span in time
    py = next(e for e in events if e["name"] == "py/all_reduce")
    nat = next(e for e in events if e["name"] == "allreduce")
    assert py["ts"] <= nat["ts"] <= py["ts"] + py["dur"] + 1e3


def test_netem_pacing_on_pipelined_path():
    """The pipelined io_uring data plane must honor per-edge
    PCCLT_WIRE_*_MAP pacing exactly like the poll loop: a 2-peer ring over
    a 100 Mbit/s emulated mesh cannot beat the wire (each peer moves
    2*(n-1)/n * payload = 4 MiB of egress at 12.5 MB/s → ≥ ~0.33 s), and
    the per-edge counters still conserve bytes exactly."""
    from pccl_tpu.comm import MasterNode
    from pccl_tpu.comm.native_bench import wire_topology

    world, count = 2, 1 << 20  # 4 MiB payload
    plane = {"PCCLT_PIPELINE": "1", "PCCLT_URING": "1",
             # small window floor so the pipeline actually windows the
             # 2 MiB stage chunks
             "PCCLT_PIPELINE_MIN_BYTES": str(256 << 10)}
    port_base = alloc_ports(span=2300)
    master = MasterNode("0.0.0.0", alloc_ports())
    master.run()
    procs = []
    try:
        with wire_topology(world, port_base, mbps=100.0) as envs:
            for r in range(world):
                cmd = [sys.executable, str(REPO / "tests" / "telemetry_peer.py"),
                       "--master-port", str(master.port), "--rank", str(r),
                       "--world", str(world), "--port-base", str(port_base),
                       "--count", str(count),
                       "--env", json.dumps({**envs[r], **plane})]
                procs.append(subprocess.Popen(cmd, stdout=subprocess.PIPE,
                                              stderr=subprocess.STDOUT,
                                              text=True))
            outs = [p.communicate(timeout=180)[0] for p in procs]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        master.interrupt()
        master.destroy()
    nbytes = count * 4
    expected = 2 * (world - 1) * nbytes // world
    for out in outs:
        r = json.loads(out.strip().splitlines()[-1])
        assert "error" not in r, out[-2000:]
        edges = r["stats"]["edges"]
        assert sum(e["tx_bytes"] for e in edges.values()) == expected
        assert sum(e["rx_bytes"] for e in edges.values()) == expected
        # the emulated wire's floor: 4 MiB egress at 12.5 MB/s. Anything
        # meaningfully under it means the new path bypassed the pacer.
        assert r["elapsed_s"] >= 0.28, \
            f"pipelined path outran the emulated wire: {r['elapsed_s']:.3f}s"


def _run_peers(master_port, world, worker, base):
    """In-process peer threads (per-comm telemetry domains keep their
    stats attributable even in one process)."""
    from pccl_tpu.comm import Communicator

    errors = []

    def peer(rank):
        comm = Communicator("127.0.0.1", master_port,
                            p2p_port=base + rank * 8,
                            ss_port=base + 512 + rank * 8,
                            bench_port=base + 1024 + rank * 8)
        try:
            comm.connect()
            deadline = time.time() + 30
            while comm.world_size < world:
                if time.time() > deadline:
                    raise TimeoutError(f"rank {rank}: world never {world}")
                if comm.are_peers_pending():
                    comm.update_topology()
                time.sleep(0.01)
            worker(comm, rank)
        except Exception as e:  # noqa: BLE001
            errors.append((rank, e))
        finally:
            comm.destroy()

    threads = [threading.Thread(target=peer, args=(r,), daemon=True)
               for r in range(world)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not [t for t in threads if t.is_alive()], "peers wedged"
    assert not errors, f"peer failures: {errors}"


def test_stats_across_peer_kick():
    """A peer violating the shared-state one-increment rule is kicked; its
    stats record the kick, the survivors' stats record the departure, and
    the in-process master's flight recorder carries the kick event with
    its reason."""
    from pccl_tpu.comm import (KickedError, MasterNode, SharedState,
                               TensorInfo, trace_enable, trace_events)

    master = MasterNode("0.0.0.0", alloc_ports())
    master.run()
    trace_enable(True)
    stats = {}
    kicked_ranks = []
    barrier = threading.Barrier(3, timeout=60)

    def worker(comm, rank):
        w = np.full(64, 1.0, dtype=np.float32)
        # round 1: everyone at revision 1 — initializes the group's
        # revision tracking (one-increment rule armed from here on)
        comm.sync_shared_state(
            SharedState([TensorInfo.from_numpy("w", w)], revision=1))
        barrier.wait()
        # round 2: rank 2 offers revision 5 (> last+1) -> master kicks it;
        # ranks 0/1 offer the legal revision 2 and complete once the
        # violator is gone
        offer = 5 if rank == 2 else 2
        try:
            comm.sync_shared_state(
                SharedState([TensorInfo.from_numpy("w", w)], revision=offer))
        except KickedError:
            kicked_ranks.append(rank)
            stats[rank] = comm.stats()
            return
        # survivors: observe the departure via a topology round
        deadline = time.time() + 30
        while comm.world_size > 2 and time.time() < deadline:
            try:
                comm.update_topology()
            except Exception:  # noqa: BLE001 — racing the disconnect
                time.sleep(0.05)
        stats[rank] = comm.stats()

    try:
        _run_peers(master.port, 3, worker, alloc_ports(span=2048))
    finally:
        master.interrupt()
        master.destroy()

    assert kicked_ranks == [2]
    assert stats[2]["counters"]["kicked"] == 1
    assert stats[2]["counters"]["syncs_failed"] >= 1
    for r in (0, 1):
        c = stats[r]["counters"]
        assert c["syncs_ok"] == 2, (r, c)
        assert c["peers_left"] >= 1, (r, c)
        assert c["kicked"] == 0
    # the in-process master fed the same recorder: the kick is an event,
    # and its reason names the revision rule
    evs = trace_events()
    kicks = [e for e in evs if e["name"] == "master_kick"]
    assert kicks, "master kick event missing from trace"
    assert any("revision" in k.get("args", {}).get("detail", "")
               for k in kicks)


def test_stats_counters_shape():
    """stats() exposes the full counter set with zero defaults and no
    edges before any p2p traffic."""
    from pccl_tpu.comm import Communicator, MasterNode

    master = MasterNode("0.0.0.0", alloc_ports())
    master.run()
    try:
        comm = Communicator("127.0.0.1", master.port,
                            p2p_port=alloc_ports(span=64))
        comm.connect()
        s = comm.stats()
        for key in ("collectives_ok", "collectives_aborted",
                    "collectives_connection_lost", "topology_updates",
                    "topology_optimizes", "syncs_ok", "syncs_failed",
                    "sync_hash_mismatches", "kicked", "peers_joined",
                    "peers_left"):
            assert s["counters"][key] == 0, (key, s)
        assert s["edges"] == {}
        comm.destroy()
    finally:
        master.interrupt()
        master.destroy()


# ---------------------------------------------------------------- profiler


def test_profiler_summary_handles_empty_sections():
    """A pre-registered/never-entered section must not render min=inf or
    divide by zero (satellite fix)."""
    from pccl_tpu.utils.profiler import Profiler, _Stat

    prof = Profiler()
    with prof.section("ran"):
        pass
    prof._stats["never"] = _Stat()
    s = prof.summary()
    assert "inf" not in s
    assert "never" in s and "ran" in s


def test_profiler_export_overwrite_guard(tmp_path):
    """export_chrome_trace(overwrite=False) refuses to clobber; the default
    keeps the historical overwrite behavior (satellite fix)."""
    from pccl_tpu.utils.profiler import Profiler

    prof = Profiler()
    with prof.section("s"):
        pass
    path = tmp_path / "t.json"
    prof.export_chrome_trace(str(path))
    prof.export_chrome_trace(str(path))  # default: silent overwrite
    with pytest.raises(FileExistsError):
        prof.export_chrome_trace(str(path), overwrite=False)


def test_profiler_merges_native_events(tmp_path):
    """Native events (absolute CLOCK_MONOTONIC µs) are re-anchored to the
    profiler's t0 so both tracks share one timeline; pre-profiler events
    clamp to 0; metadata events pass through untouched."""
    from pccl_tpu.utils.profiler import Profiler

    prof = Profiler()
    with prof.section("py"):
        time.sleep(0.002)
    now_us = time.perf_counter() * 1e6
    native = [
        {"ph": "M", "name": "process_name", "pid": 9,
         "args": {"name": "native"}},
        {"name": "allreduce", "cat": "collective", "ph": "X", "pid": 9,
         "tid": 1, "ts": now_us - 1000.0, "dur": 500.0, "args": {}},
        {"name": "ancient", "cat": "collective", "ph": "i", "pid": 9,
         "tid": 1, "ts": 1.0, "s": "t", "args": {}},
    ]
    path = tmp_path / "m.json"
    prof.export_chrome_trace(str(path), native_events=native)
    events = json.loads(path.read_text())["traceEvents"]
    by_name = {e["name"]: e for e in events if "name" in e}
    assert by_name["py"]["pid"] == 0
    # the allreduce happened ~1ms before `now`, well after prof's t0
    assert 0 < by_name["allreduce"]["ts"] < now_us
    assert by_name["ancient"]["ts"] == 0.0          # clamped, not negative
    assert "ts" not in by_name["process_name"]      # metadata untouched
    # input list was not mutated (export copies)
    assert native[1]["ts"] == now_us - 1000.0
