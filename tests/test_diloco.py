"""DiLoCo end-to-end on loopback peers.

Reference parity: the sync/async DiLoCo example loops
(/root/reference/python/examples/nanogpt_diloco/) and the mnist_diloco
convergence e2e test (/root/reference/python/tests/end_to_end/)."""

import threading
import time
from pathlib import Path

import numpy as np
import pytest

LIB = Path(__file__).resolve().parent.parent / "pccl_tpu" / "native" / "build" / "libpcclt.so"
needs_native = pytest.mark.skipif(not LIB.exists(), reason="native lib not built")


def _toy_problem(seed):
    """Linear regression: fit w to y = X @ w_true, loss = mse."""
    import jax
    import jax.numpy as jnp

    rng = np.random.RandomState(seed)
    X = jnp.asarray(rng.randn(64, 8).astype(np.float32))
    w_true = jnp.asarray(np.arange(8, dtype=np.float32))
    y = X @ w_true

    def loss_fn(params):
        pred = X @ params["w"] + params["b"]
        return jnp.mean((pred - y) ** 2)

    grad_fn = jax.jit(jax.grad(loss_fn))
    loss_jit = jax.jit(loss_fn)
    return loss_jit, grad_fn


def _inner_sgd(params, grad_fn, steps, lr=0.05):
    import jax

    for _ in range(steps):
        g = grad_fn(params)
        params = jax.tree.map(lambda p, gg: p - lr * gg, params, g)
    return params


def test_diloco_local_no_comm():
    """comm=None: outer step must still apply the update locally."""
    import jax.numpy as jnp

    from pccl_tpu.parallel.diloco import Diloco, DilocoConfig

    loss_jit, grad_fn = _toy_problem(0)
    params = {"w": jnp.zeros(8), "b": jnp.zeros(())}
    dl = Diloco(None, params, DilocoConfig(inner_steps=20, outer_lr=0.7))
    p = params
    l0 = float(loss_jit(p))
    for _ in range(5):
        p = _inner_sgd(p, grad_fn, 20)
        p = dl.outer_step(p)
    assert float(loss_jit(p)) < l0 * 0.05
    assert dl.step == 5


def test_async_diloco_sync_resets_baseline(monkeypatch):
    """sync_shared_state must join the in-flight reduce and invalidate the
    pseudo-gradient baseline — adopted params make the old baseline bogus."""
    import jax.numpy as jnp

    from pccl_tpu.parallel import diloco as dmod

    params = {"w": jnp.zeros(4)}
    dl = dmod.AsyncDiloco(None, params)
    dl.outer_step_async(params)          # sets _baseline, no comm → no-op reduce
    assert dl._baseline is not None
    monkeypatch.setattr(dmod.Diloco, "sync_shared_state",
                        lambda self, strategy=None: "info")
    assert dl.sync_shared_state() == "info"
    assert dl._baseline is None
    assert dl._inflight is None


@needs_native
@pytest.mark.parametrize("async_mode", [False, True])
def test_diloco_two_peers_converge(async_mode):
    import jax.numpy as jnp

    from pccl_tpu.comm import MasterNode
    from pccl_tpu.parallel.diloco import AsyncDiloco, Diloco, DilocoConfig

    master = MasterNode("0.0.0.0", 52000 if not async_mode else 52100)
    master.run()
    results = {}
    errors = []

    def peer(rank):
        try:
            from pccl_tpu.comm import Communicator

            base = (53000 if not async_mode else 53500) + rank * 16
            comm = Communicator("127.0.0.1", master.port, p2p_port=base,
                                ss_port=base + 4, bench_port=base + 8)
            comm.connect()
            deadline = time.time() + 30
            while comm.world_size < 2:
                if time.time() > deadline:
                    raise TimeoutError("world never reached 2")
                if comm.are_peers_pending():
                    comm.update_topology()
                time.sleep(0.01)

            loss_jit, grad_fn = _toy_problem(seed=100 + rank)  # different data shards
            params = {"w": jnp.zeros(8), "b": jnp.zeros(())}
            cls = AsyncDiloco if async_mode else Diloco
            # delayed gradients + heavy momentum oscillate on a quadratic, so
            # the async path trains with momentum off (the delay is the point
            # under test, not the momentum schedule)
            cfg = DilocoConfig(inner_steps=10, outer_lr=0.7,
                               outer_momentum=0.0 if async_mode else 0.9)
            dl = cls(comm, params, cfg)
            p = params
            for _ in range(16 if async_mode else 8):
                p = _inner_sgd(p, grad_fn, 10)
                p = (dl.outer_step_async(p) if async_mode else dl.outer_step(p))
            if async_mode:
                p = dl.finish()
            results[rank] = (np.asarray(p["w"]), float(loss_jit(p)))
            comm.destroy()
        except Exception as e:  # noqa: BLE001
            errors.append((rank, e))

    ts = [threading.Thread(target=peer, args=(r,)) for r in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=180)
    master.interrupt()
    master.destroy()
    assert not errors, f"peer failures: {errors}"
    w0, l0 = results[0]
    w1, l1 = results[1]
    # outer params must be bit-identical across peers (same averaged deltas)
    np.testing.assert_array_equal(w0, w1)
    # and close to the true solution despite different local shards
    np.testing.assert_allclose(w0, np.arange(8, dtype=np.float32), atol=0.5)


@needs_native
def test_diloco_shared_state_joiner_catchup():
    """A second peer joining late must adopt the first peer's outer state via
    sync_shared_state (reference: late-joiner flow, 03-AsyncDiloco.md)."""
    import jax.numpy as jnp

    from pccl_tpu.comm import Communicator, MasterNode, SharedStateSyncStrategy
    from pccl_tpu.parallel.diloco import Diloco, DilocoConfig

    master = MasterNode("0.0.0.0", 52200)
    master.run()
    errors = []
    adopted = {}
    barrier = threading.Barrier(2, timeout=60)

    def peer(rank):
        try:
            base = 54000 + rank * 16
            comm = Communicator("127.0.0.1", master.port, p2p_port=base,
                                ss_port=base + 4, bench_port=base + 8)
            comm.connect()
            deadline = time.time() + 30
            while comm.world_size < 2:
                if time.time() > deadline:
                    raise TimeoutError("world never reached 2")
                if comm.are_peers_pending():
                    comm.update_topology()
                time.sleep(0.01)

            params = {"w": jnp.zeros(8)}
            dl = Diloco(comm, params, DilocoConfig())
            if rank == 0:
                # advance rank 0's outer state locally before the sync
                dl.outer_params = {"w": jnp.full(8, 3.25)}
                dl.step = 4
            else:
                dl.step = 4  # same revision, stale content
            barrier.wait()
            dl.sync_shared_state(SharedStateSyncStrategy.SEND_ONLY if rank == 0
                                 else SharedStateSyncStrategy.RECEIVE_ONLY)
            adopted[rank] = np.asarray(dl.outer_params["w"])
            comm.destroy()
        except Exception as e:  # noqa: BLE001
            errors.append((rank, e))

    ts = [threading.Thread(target=peer, args=(r,)) for r in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=120)
    master.interrupt()
    master.destroy()
    assert not errors, f"peer failures: {errors}"
    np.testing.assert_array_equal(adopted[0], adopted[1])
    np.testing.assert_allclose(adopted[1], np.full(8, 3.25))


def test_diloco_pipelined_windowed_reduce():
    """comm_windows>1 + shm_staging takes the pipelined path (per-window
    D2H overlapped with per-window tagged reduces); the averaged result
    must be exact and bit-identical across peers."""
    import jax.numpy as jnp

    from pccl_tpu.comm import MasterNode
    from pccl_tpu.parallel.diloco import Diloco, DilocoConfig

    n = (2 << 20) + 321  # two windows and a ragged tail
    master = MasterNode("0.0.0.0", 52400)
    master.run()
    results = {}
    errors = []

    def peer(rank):
        try:
            from pccl_tpu.comm import Communicator

            base = 53800 + rank * 16
            comm = Communicator("127.0.0.1", master.port, p2p_port=base,
                                ss_port=base + 4, bench_port=base + 8)
            comm.connect()
            deadline = time.time() + 30
            while comm.world_size < 2:
                if time.time() > deadline:
                    raise TimeoutError("world never reached 2")
                if comm.are_peers_pending():
                    comm.update_topology()
                time.sleep(0.01)

            params = {"w": jnp.zeros((n,), jnp.float32)}
            cfg = DilocoConfig(outer_lr=1.0, outer_momentum=0.0,
                               nesterov=False, shm_staging=True,
                               comm_windows=2)
            dl = Diloco(comm, params, cfg)
            # pseudo-gradient = outer - inner = rank+1 everywhere
            inner = {"w": params["w"] - float(rank + 1)}
            out = dl.outer_step(inner)
            results[rank] = np.asarray(out["w"])
            comm.destroy()
        except Exception as e:  # noqa: BLE001
            errors.append((rank, e))

    ts = [threading.Thread(target=peer, args=(r,)) for r in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=180)
    master.interrupt()
    master.destroy()
    assert not errors, f"peer failures: {errors}"
    # avg pseudo-gradient = 1.5; lr=1, momentum 0 -> new = 0 - 1.5
    assert np.array_equal(results[0], results[1]), "bit parity across peers"
    np.testing.assert_allclose(results[0], -1.5, rtol=0)
