"""Drift-injection tests for the pcclt-verify analyses (tools/pcclt_verify).

Same contract as tests/test_pcclt_check.py: every checker must (a) pass on
a clean (synthetic or real) tree and (b) fail ACTIONABLY when one specific
defect is injected — a synthetic lock cycle, a blocking send under a state
lock, a CondVar wait holding a second mutex, a spec transition removed, a
dispatch arm orphaned from the spec. The model checker itself is kept
honest with mutation tests: break one consensus rule in a MasterModel
subclass and the invariant suite must report the violation the rule
exists to prevent (deadlock tie-break, exactly-one-abort, journaled seq
bound, resume-ack trust rule, moot-vote decline).
"""

from __future__ import annotations

import shutil
import textwrap
from pathlib import Path

import pytest

from tools.pcclt_verify import blocking, conformance, lock_graph
from tools.pcclt_verify import harvest as harvest_mod
from tools.pcclt_verify.fsm_spec import MasterModel, MGroup
from tools.pcclt_verify.model_check import (Scenario, Violation,
                                            default_scenarios, explore)

ROOT = Path(__file__).resolve().parents[1]
SRC = "pccl_tpu/native/src"


def _msgs(findings):
    return "\n".join(str(f) for f in findings)


def _fresh(checker, tree):
    """Run a libclang checker against `tree` with the harvest memo cleared
    (the memo is keyed by root, but tests reuse tmp paths via fixtures)."""
    harvest_mod._memo.pop(str(Path(tree).resolve()), None)
    return checker.check(tree)


# ------------------------------------------------------ lock-tree fixture


CLEAN_LOCKS = textwrap.dedent("""\
    #include "annotations.hpp"
    extern "C" long send(int, const void *, unsigned long, int);
    extern "C" int nanosleep(const void *, void *);
    struct A {
        pcclt::Mutex mu_a; // lock-rank: 10
        pcclt::Mutex mu_b; // lock-rank: 20
        int x PCCLT_GUARDED_BY(mu_a) = 0;
        void good() {
            pcclt::MutexLock la(mu_a);
            x = 1;
            pcclt::MutexLock lb(mu_b);
        }
    };
    struct W {
        pcclt::Mutex mu; // lock-rank: 60
        void park() PCCLT_REQUIRES(mu);
        void outer() {
            pcclt::MutexLock lk(mu);
            park();
        }
    };
    void W::park() {
        // drop-and-reacquire window: blocks with mu RELEASED
        mu.unlock();
        nanosleep(nullptr, nullptr);
        mu.lock();
    }
    int main() { A a; a.good(); W w; w.outer(); return 0; }
    """)


@pytest.fixture
def lock_tree(tmp_path):
    pytest.importorskip("clang.cindex")
    src = tmp_path / SRC
    src.mkdir(parents=True)
    (tmp_path / "pccl_tpu/native/include").mkdir(parents=True)
    shutil.copy(ROOT / SRC / "annotations.hpp", src / "annotations.hpp")
    (src / "locks.cpp").write_text(CLEAN_LOCKS)
    return tmp_path


def _append(tree: Path, code: str) -> None:
    p = tree / SRC / "locks.cpp"
    p.write_text(p.read_text().replace("int main()",
                                       textwrap.dedent(code) + "\nint main()"))


# --------------------------------------------------- lockorder injection


def test_lockorder_synthetic_tree_clean(lock_tree):
    out = _fresh(lock_graph, lock_tree)
    assert out == [], _msgs(out)


def test_lockorder_catches_missing_rank(lock_tree):
    p = lock_tree / SRC / "locks.cpp"
    p.write_text(p.read_text().replace(
        "pcclt::Mutex mu_b; // lock-rank: 20", "pcclt::Mutex mu_b;"))
    out = _fresh(lock_graph, lock_tree)
    assert any("mu_b" in f.message and "lock-rank" in f.message
               for f in out), _msgs(out)


def test_lockorder_catches_cycle_and_inversion(lock_tree):
    _append(lock_tree, """
        struct Rev {
            void bad(A &a) {
                pcclt::MutexLock lb(a.mu_b);
                pcclt::MutexLock la(a.mu_a); // opposite order to A::good
            }
        };
        """)
    out = _fresh(lock_graph, lock_tree)
    assert any("lock-order inversion" in f.message and "mu_a" in f.message
               for f in out), _msgs(out)
    assert any("cycle" in f.message and "deadlock" in f.message
               for f in out), _msgs(out)


def test_lockorder_catches_io_lock_with_children(lock_tree):
    _append(lock_tree, """
        struct Io {
            pcclt::Mutex wmu; // lock-rank: io
            void bad(A &a) {
                pcclt::MutexLock w(wmu);
                pcclt::MutexLock la(a.mu_a);
            }
        };
        """)
    out = _fresh(lock_graph, lock_tree)
    assert any("io" in f.message and "leaves" in f.message
               for f in out), _msgs(out)


# ---------------------------------------------------- blocking injection


def test_blocking_synthetic_tree_clean(lock_tree):
    # includes the REQUIRES'd drop-and-reacquire park in W: the caller
    # holds mu across the call, but park() releases it before blocking
    out = _fresh(blocking, lock_tree)
    assert out == [], _msgs(out)


def test_blocking_catches_send_under_state_lock(lock_tree):
    _append(lock_tree, """
        struct Tx {
            pcclt::Mutex smu; // lock-rank: 30
            void tx() {
                pcclt::MutexLock lk(smu);
                send(0, nullptr, 0, 0);
            }
        };
        """)
    out = _fresh(blocking, lock_tree)
    assert any("send" in f.message and "smu" in f.message
               for f in out), _msgs(out)


def test_blocking_io_tag_sanctions_the_send(lock_tree):
    _append(lock_tree, """
        struct Tx {
            pcclt::Mutex smu; // lock-rank: io
            void tx() {
                pcclt::MutexLock lk(smu);
                send(0, nullptr, 0, 0);
            }
        };
        """)
    out = _fresh(blocking, lock_tree)
    assert out == [], _msgs(out)


def test_blocking_allow_annotation_sanctions_the_site(lock_tree):
    _append(lock_tree, """
        struct Tx {
            pcclt::Mutex smu; // lock-rank: 30
            void tx() {
                pcclt::MutexLock lk(smu);
                // pcclt-verify: allow-blocking(test fixture)
                send(0, nullptr, 0, 0);
            }
        };
        """)
    out = _fresh(blocking, lock_tree)
    assert out == [], _msgs(out)


def test_blocking_catches_condvar_foreign_wait(lock_tree):
    _append(lock_tree, """
        struct Cv {
            pcclt::Mutex m1; // lock-rank: 40
            pcclt::Mutex m2; // lock-rank: 50
            pcclt::CondVar cv;
            void waitboth() {
                pcclt::MutexLock l1(m1);
                pcclt::MutexLock l2(m2);
                cv.wait(m2); // m1 stays held for the whole park
            }
        };
        """)
    out = _fresh(blocking, lock_tree)
    assert any("CondVar" in f.message and "m1" in f.message
               for f in out), _msgs(out)


def test_blocking_catches_lost_drop_window(lock_tree):
    # remove W::park's unlock: the REQUIRES'd lock is now HELD at the park
    p = lock_tree / SRC / "locks.cpp"
    p.write_text(p.read_text().replace("mu.unlock();", "").replace(
        "mu.lock();", ""))
    out = _fresh(blocking, lock_tree)
    assert any("nanosleep" in f.message and "mu" in f.message
               for f in out), _msgs(out)


# ------------------------------------------------- real-tree green gates


@pytest.mark.slow
def test_lockorder_real_tree_clean():
    out = _fresh(lock_graph, ROOT)
    assert not isinstance(out, list) or out == [], _msgs(out)


@pytest.mark.slow
def test_blocking_real_tree_clean():
    out = _fresh(blocking, ROOT)
    assert not isinstance(out, list) or out == [], _msgs(out)


# ------------------------------------------------- conformance injection


@pytest.fixture
def conf_tree(tmp_path):
    for rel in (f"{SRC}/master.cpp", f"{SRC}/master_state.cpp",
                f"{SRC}/client.cpp"):
        (tmp_path / rel).parent.mkdir(parents=True, exist_ok=True)
        shutil.copy(ROOT / rel, tmp_path / rel)
    return tmp_path


def _edit(root: Path, rel: str, old: str, new: str) -> None:
    p = root / rel
    text = p.read_text()
    assert old in text, f"fixture drift: {old!r} not in {rel}"
    p.write_text(text.replace(old, new, 1))


def test_conformance_real_tree_clean():
    out = conformance.check(ROOT)
    assert out == [], _msgs(out)


def test_conformance_copy_of_real_tree_passes(conf_tree):
    assert conformance.check(conf_tree) == []


def test_conformance_catches_arm_orphaned_from_spec(conf_tree):
    # a NEW dispatch arm the spec has never heard of
    _edit(conf_tree, f"{SRC}/master.cpp",
          "case PacketType::kC2MOptimizeTopology:",
          "case PacketType::kC2MBrandNewThing:\n"
          "                    out = state_.on_brand_new(ev.conn_id);\n"
          "                    break;\n"
          "                case PacketType::kC2MOptimizeTopology:")
    out = conformance.check(conf_tree)
    assert any("kC2MBrandNewThing" in f.message
               and "no transition" in f.message for f in out), _msgs(out)


def test_conformance_catches_spec_transition_removed(conf_tree):
    # dropping a real arm orphans the spec's modeled transition
    _edit(conf_tree, f"{SRC}/master.cpp",
          "case PacketType::kC2MSessionResume:",
          "case PacketType::kC2MTopologyUpdate: /* arm dropped */")
    out = conformance.check(conf_tree)
    assert any("kC2MSessionResume" in f.message
               and "no dispatch arm" in f.message for f in out), _msgs(out)


def test_conformance_catches_handler_mismatch(conf_tree):
    _edit(conf_tree, f"{SRC}/master.cpp",
          "out = state_.on_optimize(ev.conn_id);",
          "out = state_.on_optimize_work_done(ev.conn_id);")
    out = conformance.check(conf_tree)
    assert any("kC2MOptimizeTopology" in f.message
               and "on_optimize" in f.message for f in out), _msgs(out)


def test_conformance_catches_unmodeled_emission(conf_tree):
    _edit(conf_tree, f"{SRC}/master_state.cpp",
          "PacketType::kM2CKicked",
          "PacketType::kM2CBogusEmission")
    out = conformance.check(conf_tree)
    assert any("kM2CBogusEmission" in f.message for f in out), _msgs(out)
    # and the now-unemitted kM2CKicked is flagged as stale in the spec
    assert any("kM2CKicked" in f.message and "never does" in f.message
               for f in out), _msgs(out)


# ------------------------------------------------- model-checker passes


def _by_name(name: str) -> Scenario:
    for sc in default_scenarios():
        if sc.name == name:
            return sc
    raise AssertionError(f"no scenario {name}")


def test_model_join_during_collective_passes():
    explore(_by_name("join_during_collective"))


def test_model_local_abort_passes():
    explore(_by_name("collective_local_abort"))


def test_model_restart_lag_passes():
    explore(_by_name("restart_lag"))


@pytest.mark.slow
def test_model_default_suite_passes():
    for sc in default_scenarios():
        explore(sc)


# --------------------------------------------- model-checker mutations
# Break one consensus rule; the checker must report the violation that
# rule exists to prevent. A model checker that cannot fail is a progress
# bar, not a proof.


class NoTieBreak(MasterModel):
    """The vote-vs-commence deadlock tie-break removed: votes park even
    when the voter's group is mid-round, and nobody is ever deferred."""

    def group_mid_round(self, c):
        return False

    def defer_topology_voters(self, out, gid):
        pass


def test_mutation_no_tie_break_deadlocks():
    with pytest.raises(Violation, match="stuck world|livelock"):
        explore(_by_name("join_during_collective"), NoTieBreak)


class DoubleAbort(MasterModel):
    """The exactly-one-abort latch removed: every aborted completion
    re-broadcasts, so members can see two verdicts."""

    def on_collective_complete(self, uuid, tag, aborted):
        out = []
        c = self.clients.get(uuid)
        if c is None:
            return out
        g = self.groups.setdefault(c.group, MGroup())
        op = g.ops.get(tag)
        if op is None:
            return out
        op.completed = op.completed | {uuid}
        if aborted:
            op.any_aborted = True
            if op.commenced:  # BUG: abort_broadcast never latched
                for u in op.members:
                    if u in self.clients:
                        out.append((u, "kM2CCollectiveAbort",
                                    {"tag": tag, "aborted": 1}))
        self.check_collective(out, c.group, tag)
        return out


def test_mutation_double_abort_detected():
    with pytest.raises(Violation, match="abort"):
        explore(_by_name("collective_local_abort"), DoubleAbort)


class ForgetSeqBound(MasterModel):
    """A restarted master restarts seqs at 1 instead of resuming above the
    journaled bound: tag ranges from the previous epoch get reused."""

    @classmethod
    def restart(cls, journal, lag=False):
        m = super().restart(journal, lag)
        m.next_seq = 1  # BUG: journaled seq bound ignored
        m.seq_bound = 0
        return m


def test_mutation_forgotten_seq_bound_detected():
    with pytest.raises(Violation, match="seq"):
        explore(_by_name("restart_resume"), ForgetSeqBound)


class DistrustResume(MasterModel):
    """The resume ack's trust-the-client revision rule removed: a Done
    that raced the crash is forgotten, and the master later kicks a
    correct client for offering the revision it legitimately reached."""

    def on_session_resume(self, uuid, last_revision):
        return super().on_session_resume(uuid, 0)  # BUG: ignore the client


def test_mutation_distrust_resume_kicks_correct_client():
    sc = Scenario("restart_lag3",
                  (("a", 0, ("sync", "sync", "sync")),
                   ("b", 0, ("sync", "sync", "sync"))),
                  journal=True, max_restarts=1, lag=True, staged=True)
    explore(sc)  # the real rules absorb the lost append
    with pytest.raises(Violation, match="kick"):
        explore(sc, DistrustResume)


class NoMootDecline(MasterModel):
    """The moot-vote decline removed: when the pending joiner a vote was
    cast for departs, the standing vote parks its owner forever."""

    def remove_client(self, out, uuid, gid):
        self.abort_group_collectives(out, gid)
        g = self.groups.get(gid)
        if g is not None:
            for op in g.ops.values():
                op.initiated = op.initiated - {uuid}
                op.completed = op.completed - {uuid}
            for tag in [t for t, op in g.ops.items()
                        if not op.commenced and not op.initiated]:
                del g.ops[tag]
            if not self.group_members(gid) and not self.group_frozen(gid):
                self.groups[gid] = MGroup()
                if self.journal is not None:
                    self.journal.record_group(gid, 0, False)
        self.recheck_all(out)  # BUG: standing votes never declined


def test_mutation_no_moot_decline_strands_voter():
    # needs TWO accepted members: with one, the lone vote trivially runs
    # the round; with two, `a`'s vote parks until `b` votes — and when the
    # pending joiner dies, `b` never will (are_peers_pending == false)
    sc = Scenario("moot_vote",
                  (("a", 0, ()), ("b", 0, ()), ("j", 0, ())),
                  disconnects=("j",))
    explore(sc)  # the decline keeps this live
    with pytest.raises(Violation, match="stuck world|livelock"):
        explore(sc, NoMootDecline)


# ------------------------------------------------- data-plane checker
# The frame-flow model checker (dataplane_check): clean on the real
# tree, conformance drift caught both ways, and the invariant suite
# kept honest by single-rule mutations of the SinkTable / ack models.


from tools.pcclt_verify import dataplane_check as dp
from tools.pcclt_verify.dataplane_spec import AckModel, TableModel


def _dp_scenario(name: str) -> dp.Scenario:
    for sc in dp.default_scenarios():
        if sc.name == name:
            return sc
    raise AssertionError(f"no dataplane scenario {name}")


def test_dataplane_real_tree_clean():
    out = dp.check(ROOT)
    assert out == [], _msgs(out)


def test_dataplane_default_suite_explores_all_faults():
    # every adversarial action class must actually fire somewhere in the
    # suite — a fault the explorer never schedules is a vacuous guarantee
    import collections
    counts: "collections.Counter[str]" = collections.Counter()
    orig = dp.apply_action

    def counting(w, act):
        counts[act[0]] += 1
        return orig(w, act)

    dp.apply_action = counting
    try:
        for sc in dp.default_scenarios():
            dp.explore(sc)
    finally:
        dp.apply_action = orig
    for needed in ("dup_frame", "relay_dup", "cancel", "lose", "die",
                   "seeder_die", "resource", "suspect", "confirm",
                   "reissue"):
        assert counts[needed] > 0, f"suite never explores {needed!r}"


# ---- mutations: break one rule, the invariant that rule protects fails


class NoDedup(TableModel):
    """First-arrival-wins dedupe removed: a duplicated direct frame is
    claimed and committed a second time, and the commit-side overlap
    accounting is silenced with it."""

    def dedup_direct(self, s, off, end):
        return False

    def dup_on_commit(self, length, fresh):
        return 0


def test_dataplane_mutation_no_dedup_breaks_conservation():
    with pytest.raises(dp.Violation, match="conservation"):
        dp.explore(_dp_scenario("stripe_reorder_dup"), NoDedup)


class NoAckMerge(AckModel):
    """Interval merge replaced by a summed byte total: a window acked
    twice counts double, so coverage of [0, n) is claimed after 2 acks
    of the same [0, n/2) sub-range."""

    def __init__(self):
        super().__init__()
        self.totals: "dict[int, int]" = {}

    def copy(self):
        a = super().copy()
        a.totals = dict(self.totals)
        return a

    def freeze(self):
        return (super().freeze(), tuple(sorted(self.totals.items())))

    def note_ack(self, tag, off, length):
        self.totals[tag] = self.totals.get(tag, 0) + length
        super().note_ack(tag, off, length)

    def ack_covered(self, tag, off, length):
        return self.totals.get(tag, 0) >= length


def test_dataplane_mutation_no_ack_merge_unsound_cancel():
    # the duplicated relay window in relay_vs_direct double-acks [0, 2);
    # the summed total then "covers" [0, 4) and cancels the direct zombie
    # while bytes [2, 4) never arrived
    with pytest.raises(dp.Violation, match="ack-retire unsound"):
        dp.explore(_dp_scenario("relay_vs_direct"), TableModel, NoAckMerge)


class NoUnretire(TableModel):
    """register_sink no longer removes the previous incarnation's retire
    marker: round-2 relay deliveries are silently eaten by the stale
    marker while their end-to-end acks still fire and cancel live
    copies whose bytes never landed."""

    def unretire_on_register(self, tag):
        pass


def test_dataplane_mutation_no_unretire_detected():
    with pytest.raises(dp.Violation,
                       match="ack-retire unsound|stuck world|livelock"):
        dp.explore(_dp_scenario("retire_tag_reuse"), NoUnretire)


# ---- conformance drift: edit the real dispatch surface, catch it


@pytest.fixture
def dp_tree(tmp_path):
    for rel in (f"{SRC}/sockets.hpp", f"{SRC}/sockets.cpp",
                f"{SRC}/client.cpp", f"{SRC}/reduce.cpp",
                f"{SRC}/telemetry.hpp", f"{SRC}/ss_chunk.hpp"):
        (tmp_path / rel).parent.mkdir(parents=True, exist_ok=True)
        shutil.copy(ROOT / rel, tmp_path / rel)
    return tmp_path


def test_dataplane_conformance_copy_of_real_tree_passes(dp_tree):
    assert dp.conformance_findings(dp_tree) == []


def test_dataplane_conformance_catches_new_kind(dp_tree):
    _edit(dp_tree, f"{SRC}/sockets.hpp",
          "kChunkHdr = 12,",
          "kChunkHdr = 12,\n        kBrandNewKind = 13,")
    out = dp.conformance_findings(dp_tree)
    assert any("kBrandNewKind" in f.message and "no entry" in f.message
               for f in out), _msgs(out)


def test_dataplane_conformance_catches_value_drift(dp_tree):
    _edit(dp_tree, f"{SRC}/sockets.hpp",
          "kChunkHdr = 12,", "kChunkHdr = 14,")
    out = dp.conformance_findings(dp_tree)
    assert any("kChunkHdr" in f.message and "realign" in f.message
               for f in out), _msgs(out)


def test_dataplane_conformance_catches_rearmed_dispatch(dp_tree):
    # splitting kRelayAck out of nothing — merge it into the kChunkReq
    # arm: the arm partition no longer matches the spec's grouping
    _edit(dp_tree, f"{SRC}/sockets.cpp",
          "        if (kind == kChunkReq) {",
          "        if (kind == kChunkReq || kind == kRelayAck) {")
    out = dp.conformance_findings(dp_tree)
    assert any("kChunkReq" in f.message and "RX_DISPATCH" in f.message
               for f in out), _msgs(out)


def test_dataplane_conformance_catches_lost_fastpath_marker(dp_tree):
    _edit(dp_tree, f"{SRC}/sockets.cpp",
          "// kData — sink fast path", "// data sink path")
    out = dp.conformance_findings(dp_tree)
    assert any("sink fast path" in f.message for f in out), _msgs(out)


def test_dataplane_conformance_catches_unrouted_hook(dp_tree):
    _edit(dp_tree, f"{SRC}/client.cpp",
          "set_chunk_req_handler", "zz_chunk_req_handler")
    out = dp.conformance_findings(dp_tree)
    assert any("set_chunk_req_handler" in f.message for f in out), _msgs(out)
