"""End-to-end: the example training loops over a real master + peer processes.

Reference parity: the reference's subprocess-orchestrated e2e tests
(/root/reference/python/tests/end_to_end/ — basic reduce, mnist_ddp,
mnist_diloco convergence) — a pytest launches a master + N peer OS processes
on loopback and asserts exit codes. Dataset here is synthetic (zero-egress).
"""

from __future__ import annotations

import os
import subprocess
import sys
import time
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
LIB = REPO / "pccl_tpu" / "native" / "build" / "libpcclt.so"
pytestmark = pytest.mark.skipif(not LIB.exists(), reason="native lib not built")

from conftest import alloc_ports as _next_port


def _peer_env() -> dict:
    env = dict(os.environ)
    # each peer process = one "slice" with a small virtual CPU mesh
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env["JAX_PLATFORMS"] = "cpu"
    return env


def _run_example(script: Path, n_peers: int, extra: list[str],
                 timeout: float = 600):
    from pccl_tpu.comm import MasterNode

    master = MasterNode("0.0.0.0", _next_port())
    master.run()
    procs = []
    try:
        base = _next_port(span=64 * n_peers)
        for r in range(n_peers):
            # same --seed everywhere: peers must start from identical params
            # (data shards already differ via the per-peer base-port rng)
            cmd = [sys.executable, str(script),
                   "--master-port", str(master.port),
                   "--base-port", str(base + r * 16),
                   "--min-world", str(n_peers)] + extra
            procs.append(subprocess.Popen(cmd, stdout=subprocess.PIPE,
                                          stderr=subprocess.STDOUT, text=True,
                                          env=_peer_env()))
        outs = []
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
        for p, out in zip(procs, outs):
            assert p.returncode == 0, f"{script.name} peer failed:\n{out[-2000:]}"
        return outs
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        master.interrupt()
        master.destroy()


def _final_losses(out: str):
    for ln in out.splitlines():
        if ln.startswith("FINAL"):
            parts = dict(kv.split("=") for kv in ln.split()[1:])
            return float(parts["first_loss"]), float(parts["last_loss"])
    raise AssertionError(f"no FINAL line in output:\n{out[-2000:]}")


def test_nanogpt_ddp_two_peers():
    outs = _run_example(REPO / "examples" / "nanogpt_ddp" / "train_ddp.py", 2,
                        ["--steps", "10", "--batch", "4"])
    for out in outs:
        first, last = _final_losses(out)
        assert last < first
        assert "world 2" in out  # actually trained together


def test_sync_diloco_two_peers():
    outs = _run_example(
        REPO / "examples" / "nanogpt_diloco" / "sync_diloco.py", 2,
        ["--outer-steps", "4", "--inner-steps", "5", "--batch", "4"])
    for out in outs:
        first, last = _final_losses(out)
        assert last < first
        assert "world 2" in out


def test_async_diloco_two_peers():
    outs = _run_example(
        REPO / "examples" / "nanogpt_diloco" / "async_diloco.py", 2,
        ["--outer-steps", "5", "--inner-steps", "5", "--batch", "4"])
    for out in outs:
        first, last = _final_losses(out)
        assert last < first
        assert "world 2" in out


# --- real-data convergence (reference: mnist_ddp / mnist_diloco e2e) ---
# char-level LM on real text (python stdlib sources, common.text_corpus);
# the model must actually LEARN — a substantial loss drop is asserted, not
# just any decrease. Solo calibration: 5.66 -> 2.80 in 60 steps.


def test_nanogpt_ddp_chars_convergence():
    outs = _run_example(
        REPO / "examples" / "nanogpt_ddp" / "train_ddp.py", 2,
        ["--data", "text", "--steps", "40", "--batch", "8", "--lr", "3e-3"])
    for out in outs:
        first, last = _final_losses(out)
        assert last < first - 1.0, f"insufficient learning: {first} -> {last}"
        assert "world 2" in out


def test_sync_diloco_chars_convergence():
    # --shm-staging: the real-training loop also exercises the registered
    # zero-copy transport (peers share this host)
    outs = _run_example(
        REPO / "examples" / "nanogpt_diloco" / "sync_diloco.py", 2,
        ["--data", "text", "--outer-steps", "5", "--inner-steps", "10",
         "--batch", "8", "--inner-lr", "3e-3", "--shm-staging"])
    for out in outs:
        first, last = _final_losses(out)
        # first_loss is captured after warmup inside the first outer round,
        # so the visible drop is smaller than DDP's full-curve drop
        assert last < first - 0.5, f"insufficient learning: {first} -> {last}"
        assert "world 2" in out


def test_llama_diloco_chars_convergence():
    """Family parity for the flagship e2e: llama must LEARN through the
    full DiLoCo loop (inner AdamW + pseudo-gradient ring + outer Nesterov)
    on real text, with the same substantial-drop bound as the GPT twin —
    not just `last < first`. Proves the second family rides the whole
    training substrate, not only the DDP demo."""
    # The heaviest example e2e (2 llama peers x 150 steps) is sensitive
    # to full-suite host load (a descheduled peer can get churn-kicked on
    # a 1-core box); one retry absorbs that while the learning bound
    # itself stays strict — it passes solo deterministically.
    for attempt in (1, 2):
        try:
            outs = _run_example(
                REPO / "examples" / "nanogpt_diloco" / "sync_diloco.py", 2,
                ["--family", "llama", "--data", "text", "--outer-steps", "5",
                 "--inner-steps", "30", "--batch", "8", "--inner-lr", "3e-3"])
            for out in outs:
                first, last = _final_losses(out)
                # llama-nano descends fast then grinds: by the time the
                # first loss is reported (after the first outer round's 30
                # inner steps) it is already ~2.8-3.2, so a fixed DELTA
                # bound would reward stopping early. Assert the absolute
                # level instead: 2.7 is well below the first report and
                # only reachable by learning through the full run
                # (calibrated 2.35-2.41; cold start is ~5.5).
                assert last < 2.7, f"insufficient learning: {first} -> {last}"
                assert last < first, f"loss rose: {first} -> {last}"
                assert "world 2" in out
            return
        except AssertionError:
            if attempt == 2:
                raise
            print("retrying llama convergence e2e after a load-flaky run",
                  flush=True)


def test_llama_ddp_two_peers():
    """The llama family rides the same DDP loop end-to-end (--family
    dispatches model init/loss and the tensor-parallel sharding rules)."""
    outs = _run_example(REPO / "examples" / "nanogpt_ddp" / "train_ddp.py", 2,
                        ["--family", "llama", "--steps", "10", "--batch", "4"])
    for out in outs:
        first, last = _final_losses(out)
        assert last < first
        assert "world 2" in out


def test_nanogpt_ddp_grad_accum():
    """--grad-accum 2: the loop scans 2 microbatches per step and still
    moves ONE averaged gradient over the ring (reference
    gradient_accumulation_steps)."""
    outs = _run_example(REPO / "examples" / "nanogpt_ddp" / "train_ddp.py", 2,
                        ["--steps", "8", "--batch", "4", "--grad-accum", "2"])
    for out in outs:
        first, last = _final_losses(out)
        assert last < first
        assert "world 2" in out


def test_nanogpt_ddp_schedule_and_eval():
    """--lr-schedule cosine + periodic held-out eval (reference get_lr +
    estimate_loss): the run trains and emits eval lines from a disjoint
    data stream."""
    outs = _run_example(
        REPO / "examples" / "nanogpt_ddp" / "train_ddp.py", 2,
        ["--steps", "10", "--batch", "4", "--lr-schedule", "cosine",
         "--warmup-steps", "2", "--eval-every", "5"])
    for out in outs:
        first, last = _final_losses(out)
        assert last < first
        assert "eval step 4 loss" in out and "eval step 9 loss" in out


def test_nanogpt_ddp_checkpoint_resume(tmp_path):
    """Checkpoint + resume in the DDP loop (reference ckpt.pt save/resume):
    a second invocation picks up params/opt_state at the newest snapshot
    and runs only the remaining steps."""
    script = REPO / "examples" / "nanogpt_ddp" / "train_ddp.py"
    base = [sys.executable, str(script), "--solo", "--batch", "4",
            "--checkpoint-dir", str(tmp_path / "ck"),
            "--checkpoint-every", "3"]
    r1 = subprocess.run(base + ["--steps", "6"], capture_output=True,
                        text=True, env=_peer_env(), timeout=300)
    assert r1.returncode == 0, r1.stdout[-2000:] + r1.stderr[-2000:]
    r2 = subprocess.run(base + ["--steps", "9"], capture_output=True,
                        text=True, env=_peer_env(), timeout=300)
    assert r2.returncode == 0, r2.stdout[-2000:] + r2.stderr[-2000:]
    assert "resumed from step 6" in r2.stdout
    assert "step 6 " in r2.stdout and "step 8 " in r2.stdout
    assert "step 5 " not in r2.stdout  # did NOT redo pre-resume steps


def test_nanogpt_ddp_late_join_adopts_state():
    """A peer joining mid-run must ADOPT the cohort's params/opt/step via
    the per-step shared-state election (reference train_pccl.py keeps its
    model in the pccl shared state for exactly this) — not ring-average
    its seed params against a trained model."""
    from pccl_tpu.comm import MasterNode

    master = MasterNode("0.0.0.0", _next_port())
    master.run()
    script = REPO / "examples" / "nanogpt_ddp" / "train_ddp.py"
    base = _next_port(span=64)

    def spawn(port, extra):
        cmd = [sys.executable, str(script), "--master-port", str(master.port),
               "--base-port", str(port), "--steps", "400", "--batch", "4",
               "--block", "128", "--connect-timeout", "300"] + extra
        return subprocess.Popen(cmd, stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT, text=True,
                                env=_peer_env())
    # deterministic gate: spawn B only once A's own output shows training
    # under way (a fixed sleep races A finishing all steps on a fast box —
    # 400 steps at block 128 gives B's cold jax start a wide window)
    import threading

    a = spawn(base, ["--min-world", "1"])
    a_lines = []
    pump = threading.Thread(
        target=lambda: a_lines.extend(iter(a.stdout.readline, "")),
        daemon=True)
    pump.start()
    deadline = time.time() + 300
    while not any(ln.startswith("step 5 ") for ln in a_lines):
        assert time.time() < deadline and a.poll() is None, \
            "A never reached step 5:\n" + "".join(a_lines)[-3000:]
        time.sleep(0.2)
    b = spawn(base + 16, ["--min-world", "2"])
    try:
        b_out, _ = b.communicate(timeout=420)
        assert b.returncode == 0, b_out[-3000:]
        a.wait(timeout=420)
        pump.join(timeout=10)
        a_out = "".join(a_lines)
        assert a.returncode == 0, a_out[-3000:]
        outs = [a_out, b_out]
    finally:
        for p in (a, b):
            if p.poll() is None:
                p.kill()
        master.interrupt()
        master.destroy()
    # B adopted a nonzero step from the election instead of starting at 0
    import re

    m = re.search(r"adopted shared state at step (\d+)", outs[1])
    assert m and int(m.group(1)) > 0, outs[1][-3000:]
    assert "world 2" in outs[0]
