"""Shared-state distribution scenario suite.

Reference parity: ccoip/tests/end_to_end/test_shared_state_distribution.cpp
(3,216 LoC, 24 scenarios). Each test here mirrors a reference scenario and
asserts the same accept / kick / retransmit outcome:

- basic distribution + no-retransmit-when-identical   (TestBasic,
  TestNoSyncIdenticalSharedState)
- partial dirty-key retransmission                    (TestPartialSync...)
- popular-hash election, single + multiple keys       (TestPopularHash...)
- multi-step advancement                              (TestMultiStepAdvancement)
- drag-along peers with / without advancing content   (TestDragAlongClient...)
- one-increment rule: violation kick + resume init    (TestOneIncrementRule...)
- key-set mask mismatch kick                          (TestSharedStateMaskMismatchKick)
- strategy kicks: both-rx-only, both-tx-only,         (TestBothReceiveOnly...,
  enforce-popular no-mixing                            TestDifferentSharedStatet...,
                                                       TestEnforcePopluar...)
- peer-group isolation with different keys            (TestNoSyncIdentical...PeerGroups...)
"""

import os
import threading
import time
from pathlib import Path

import numpy as np
import pytest

LIB = Path(__file__).resolve().parent.parent / "pccl_tpu" / "native" / "build" / "libpcclt.so"
pytestmark = pytest.mark.skipif(not LIB.exists(), reason="native lib not built")

from conftest import alloc_ports


@pytest.fixture
def master():
    from pccl_tpu.comm import MasterNode

    m = MasterNode("0.0.0.0", alloc_ports())
    m.run()
    yield m
    m.interrupt()
    m.destroy()


def _run_peers(master_port, world, worker, groups=None, timeout=120):
    """Run `world` client threads; worker(comm, rank) may return a value.
    Returns ({rank: result}, {rank: exception}) so scenarios can assert
    which peers succeeded, which were kicked, and what bytes moved."""
    from pccl_tpu.comm import Communicator

    results, errors = {}, {}

    def peer(rank):
        # all-ephemeral listener ports: the handshake advertises the kernel-
        # assigned ports, so scenario tests can never collide on port ranges
        comm = Communicator("127.0.0.1", master_port,
                            peer_group=0 if groups is None else groups[rank])
        try:
            comm.connect()
            deadline = time.time() + 30
            while comm.global_world_size < world:
                if time.time() > deadline:
                    raise TimeoutError(f"rank {rank}: world never reached {world}")
                if comm.are_peers_pending():
                    comm.update_topology()
                time.sleep(0.01)
            results[rank] = worker(comm, rank)
        except Exception as e:  # noqa: BLE001
            errors[rank] = e
        finally:
            comm.destroy()

    threads = [threading.Thread(target=peer, args=(r,)) for r in range(world)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=timeout)
    return results, errors


def _sync(comm, arrays, revision, strategy=None):
    from pccl_tpu.comm import SharedState, SharedStateSyncStrategy, TensorInfo

    st = SharedState([TensorInfo.from_numpy(k, v) for k, v in arrays.items()],
                     revision=revision)
    if strategy is None:
        strategy = SharedStateSyncStrategy.ENFORCE_POPULAR
    return comm.sync_shared_state(st, strategy)


# ---------------------------------------------------------------- basic


def test_basic_distribution(master):
    """Reference TestBasic: on a 1-vs-1 content split either peer may win the
    election (the reference accepts both directions); exactly value_size bytes
    cross the wire once and both peers converge."""
    nbytes = 1024 * 4

    def worker(comm, rank):
        w = np.full(1024, 42.0 if rank == 0 else 0.0, dtype=np.float32)
        info = _sync(comm, {"w": w}, revision=1)
        return info.tx_bytes, info.rx_bytes, float(w[0])

    results, errors = _run_peers(master.port, 2, worker)
    assert not errors, errors
    assert results[0][2] == results[1][2]  # converged
    sent = {0: results[0][:2], 1: results[1][:2]}
    assert sorted(sent.values()) == [(0, nbytes), (nbytes, 0)]


def test_no_retransmit_identical(master):
    """Reference TestNoSyncIdenticalSharedState: identical content on every
    peer -> zero bytes in either direction across 5 advancing revisions."""

    def worker(comm, rank):
        w = np.full(512, 7.0, dtype=np.float32)
        stats = []
        for rev in range(1, 6):
            info = _sync(comm, {"w": w}, revision=rev)
            stats.append((info.tx_bytes, info.rx_bytes, info.revision))
        return stats

    results, errors = _run_peers(master.port, 2, worker)
    assert not errors, errors
    for rank in (0, 1):
        for step, (tx, rx, rev) in enumerate(results[rank]):
            assert (tx, rx) == (0, 0)
            assert rev == step + 1


def test_partial_dirty_keys(master):
    """Reference TestPartialSyncPartiallyDirtyState: peers agree on one key
    and differ on the other -> only the dirty key is retransmitted."""

    def worker(comm, rank):
        same = np.full(256, 3.0, dtype=np.float32)
        diff = np.full(256, 5.0 if rank == 0 else 0.0, dtype=np.float32)
        info = _sync(comm, {"same": same, "diff": diff}, revision=1)
        assert same[0] == 3.0
        return info.tx_bytes, info.rx_bytes, float(diff[0])

    results, errors = _run_peers(master.port, 2, worker)
    assert not errors, errors
    # only the dirty key's bytes move, in exactly one direction (either peer
    # may win the 1-vs-1 election, as in the reference)
    assert results[0][2] == results[1][2]
    sent = {0: results[0][:2], 1: results[1][:2]}
    assert sorted(sent.values()) == [(0, 256 * 4), (256 * 4, 0)]


# ------------------------------------------------------------- election


def test_popular_hash_prevalence(master):
    """Reference TestPopularHashPrevelance: 2-vs-1 content split; the
    minority peer adopts the majority content, majority peers move 0 rx."""

    def worker(comm, rank):
        w = np.full(128, 1.0 if rank < 2 else 9.0, dtype=np.float32)
        info = _sync(comm, {"w": w}, revision=1)
        np.testing.assert_allclose(w, np.full(128, 1.0))
        return info.tx_bytes, info.rx_bytes

    results, errors = _run_peers(master.port, 3, worker)
    assert not errors, errors
    assert results[2] == (0, 128 * 4)
    assert results[0][1] == 0 and results[1][1] == 0
    assert results[0][0] + results[1][0] == 128 * 4  # exactly one distributor


def test_popular_prevalence_multiple_keys(master):
    """Reference TestPopularHashPrevalenceWithMultipleKeys: the minority peer
    is dirty on both keys; retransmission covers both."""

    def worker(comm, rank):
        a = np.full(64, 1.0 if rank < 2 else 8.0, dtype=np.float32)
        b = np.full(32, 2.0 if rank < 2 else 9.0, dtype=np.float64)
        info = _sync(comm, {"a": a, "b": b}, revision=1)
        np.testing.assert_allclose(a, 1.0)
        np.testing.assert_allclose(b, 2.0)
        return info.tx_bytes, info.rx_bytes

    results, errors = _run_peers(master.port, 3, worker)
    assert not errors, errors
    assert results[2] == (0, 64 * 4 + 32 * 8)


def test_multi_step_advancement(master):
    """Reference TestMultiStepAdvancement: all peers advance revision and
    content in lockstep; no retransmissions ever occur."""

    def worker(comm, rank):
        stats = []
        for rev in range(1, 6):
            w = np.full(128, float(rev), dtype=np.float32)
            info = _sync(comm, {"w": w}, revision=rev)
            stats.append((info.tx_bytes, info.rx_bytes))
        return stats

    results, errors = _run_peers(master.port, 3, worker)
    assert not errors, errors
    for rank in results:
        assert all(s == (0, 0) for s in results[rank])


# ----------------------------------------------------------- drag-along


def test_drag_along_no_advance(master):
    """Reference TestDragAlongClientNoAdvancedStateContents: a peer that
    re-offers its adopted (now outdated) revision with MATCHING content
    receives nothing — the revision alone never forces retransmission."""
    num_steps = 4

    def worker(comm, rank):
        w = np.full(256, 42.0, dtype=np.float32) if rank < 2 else \
            np.zeros(256, dtype=np.float32)
        stats = []
        rev = 1
        for step in range(num_steps):
            if rank < 2:
                rev = step + 1
            info = _sync(comm, {"w": w}, revision=rev)
            rev = info.revision  # drag-along peers adopt the canonical revision
            stats.append((info.tx_bytes, info.rx_bytes, info.revision))
            assert w[0] == 42.0
        return stats

    results, errors = _run_peers(master.port, 3, worker)
    assert not errors, errors
    # step 0: dragged peer receives the full value once; afterwards content
    # matches and only the revision advances
    assert results[2][0][1:] == (256 * 4, 1)
    for step in range(1, num_steps):
        assert results[2][step] == (0, 0, step + 1)


def test_drag_along_with_advancing_content(master):
    """Reference TestDragAlongClientWithAdvancedStateContents: content
    advances every step -> the dragged peer re-receives the full state each
    step."""
    num_steps = 4

    def worker(comm, rank):
        w = np.zeros(256, dtype=np.float32)
        stats = []
        rev = 0
        for step in range(num_steps):
            if rank < 2:
                w[:] = float(step + 1)
                rev = step + 1
            info = _sync(comm, {"w": w}, revision=rev)
            rev = info.revision
            stats.append((info.tx_bytes, info.rx_bytes))
            assert w[0] == float(step + 1)
        return stats

    results, errors = _run_peers(master.port, 3, worker)
    assert not errors, errors
    for step in range(1, num_steps):  # step 0: all peers start at zeros
        assert results[2][step] == (0, 256 * 4)


# ------------------------------------------------------ one-increment rule


def test_one_increment_violation_kick(master):
    """Reference TestOneIncrementRuleViolationSimple: a peer that skips a
    revision is kicked; the remaining peer's same-round sync fails loudly
    instead of silently re-syncing at a stale revision."""
    from pccl_tpu.comm import (ConnectionLostError, KickedError,
                               OperationAbortedError)

    def worker(comm, rank):
        w = np.full(64, 1.0, dtype=np.float32)
        _sync(comm, {"w": w}, revision=1)  # both at rev 1: ok
        if rank == 0:
            _sync(comm, {"w": w}, revision=3)  # skips rev 2: kicked
        else:
            _sync(comm, {"w": w}, revision=1)  # re-offer: failed round

    results, errors = _run_peers(master.port, 2, worker)
    assert set(errors) == {0, 1}, (results, errors)
    assert isinstance(errors[0], (KickedError, ConnectionLostError))
    assert isinstance(errors[1], OperationAbortedError)


def test_one_increment_initialization_resume(master):
    """Reference TestOneIncrementRuleViolationInitialization: the first-ever
    sync may use any revision (logical resume); a peer starting at 0 is
    dragged up to the resumed revision."""

    def worker(comm, rank):
        w = np.full(128, 42.0, dtype=np.float32) if rank == 0 else \
            np.zeros(128, dtype=np.float32)
        info = _sync(comm, {"w": w}, revision=13 if rank == 0 else 0)
        assert w[0] == 42.0
        return info.tx_bytes, info.rx_bytes, info.revision

    results, errors = _run_peers(master.port, 2, worker)
    assert not errors, errors
    assert results[0] == (128 * 4, 0, 13)
    assert results[1] == (0, 128 * 4, 13)


def test_same_revision_reoffer_fails(master):
    """Strict one-increment: a whole group re-offering an already-synced
    revision gets a failed round (surfaced error), not a silent re-sync."""
    from pccl_tpu.comm import OperationAbortedError

    def worker(comm, rank):
        w = np.full(64, 1.0, dtype=np.float32)
        _sync(comm, {"w": w}, revision=1)
        with pytest.raises(OperationAbortedError):
            _sync(comm, {"w": w}, revision=1)

    _, errors = _run_peers(master.port, 2, worker)
    assert not errors, errors


# ------------------------------------------------------------ mask kicks


def test_mask_mismatch_kick(master):
    """Reference TestSharedStateMaskMismatchKick: the peer whose key set
    disagrees with the elected mask is kicked; the majority completes."""
    from pccl_tpu.comm import ConnectionLostError, KickedError

    def worker(comm, rank):
        if rank < 2:
            w = np.full(64, 1.0, dtype=np.float32)
            info = _sync(comm, {"key1": w}, revision=1)
            # survivors re-run the round after the kick and succeed
            info2 = _sync(comm, {"key1": w}, revision=2)
            return (info.rx_bytes, info2.rx_bytes)
        w = np.full(64, 1.0, dtype=np.float32)
        _sync(comm, {"key2": w}, revision=1)

    results, errors = _run_peers(master.port, 3, worker)
    assert set(errors) == {2}, (results, errors)
    assert isinstance(errors[2], (KickedError, ConnectionLostError))
    assert results[0] == (0, 0) and results[1] == (0, 0)


def test_dtype_mismatch_kick(master):
    """Key names match but dtypes differ -> key-set mismatch kick for the
    minority peer (mask comparison includes dtype/count/flags)."""
    from pccl_tpu.comm import ConnectionLostError, KickedError

    def worker(comm, rank):
        if rank < 2:
            w = np.full(64, 1.0, dtype=np.float32)
        else:
            w = np.full(64, 1.0, dtype=np.float64)
        _sync(comm, {"w": w}, revision=1)

    results, errors = _run_peers(master.port, 3, worker)
    assert set(errors) == {2}, (results, errors)
    assert isinstance(errors[2], (KickedError, ConnectionLostError))


# -------------------------------------------------------- strategy kicks


def test_both_receive_only_kick_same_content(master):
    """Reference TestBothReceiveOnlyStrategyKickSameContent: if every peer is
    rx-only there is no candidate content to elect; all are kicked — even
    when their contents happen to agree."""
    from pccl_tpu.comm import (ConnectionLostError, KickedError,
                               SharedStateSyncStrategy)

    def worker(comm, rank):
        w = np.full(64, 1.0, dtype=np.float32)
        _sync(comm, {"w": w}, revision=1,
              strategy=SharedStateSyncStrategy.RECEIVE_ONLY)

    results, errors = _run_peers(master.port, 2, worker)
    assert set(errors) == {0, 1}, (results, errors)
    for e in errors.values():
        assert isinstance(e, (KickedError, ConnectionLostError))


def test_both_send_only_different_content_kick(master):
    """Reference TestDifferentSharedStatetContentBothSendOnlyStrategyKick:
    two tx-only peers with different content — the election loser would have
    to request state, which tx-only forbids, so exactly one peer is kicked."""
    from pccl_tpu.comm import SharedStateSyncStrategy

    def worker(comm, rank):
        w = np.full(64, float(rank), dtype=np.float32)
        _sync(comm, {"w": w}, revision=1,
              strategy=SharedStateSyncStrategy.SEND_ONLY)

    results, errors = _run_peers(master.port, 2, worker)
    assert len(errors) == 1, (results, errors)


def test_both_send_only_same_content_ok(master):
    """Two tx-only peers with identical content: nothing to distribute, no
    kick, zero bytes."""
    from pccl_tpu.comm import SharedStateSyncStrategy

    def worker(comm, rank):
        w = np.full(64, 5.0, dtype=np.float32)
        info = _sync(comm, {"w": w}, revision=1,
                     strategy=SharedStateSyncStrategy.SEND_ONLY)
        return info.tx_bytes, info.rx_bytes

    results, errors = _run_peers(master.port, 2, worker)
    assert not errors, errors
    assert results[0] == (0, 0) and results[1] == (0, 0)


@pytest.mark.parametrize("other", ["RECEIVE_ONLY", "SEND_ONLY"])
def test_enforce_popular_no_mixing(master, other):
    """Reference TestEnforcePopluarSyncStrategyNoMixingWith{ReceiveOnly,
    SendOnly}: enforce-popular is all-or-nothing; the peer declaring a
    different strategy is kicked and the enforce-popular peer completes."""
    from pccl_tpu.comm import (ConnectionLostError, KickedError,
                               SharedStateSyncStrategy)

    def worker(comm, rank):
        w = np.full(64, 1.0, dtype=np.float32)
        strategy = (SharedStateSyncStrategy.ENFORCE_POPULAR if rank == 0
                    else SharedStateSyncStrategy[other])
        info = _sync(comm, {"w": w}, revision=1, strategy=strategy)
        return info.tx_bytes, info.rx_bytes

    results, errors = _run_peers(master.port, 2, worker)
    assert set(errors) == {1}, (results, errors)
    assert isinstance(errors[1], (KickedError, ConnectionLostError))
    assert results[0] == (0, 0)


def test_tx_only_revision_lag_kick(master):
    """A tx-only peer whose revision lags the group is kicked even when its
    content matches the mask: tx-only peers may never be assigned to request
    state, and a revision-outdated peer is such an assignee
    (reference: ccoip_master_handler.cpp:667-697)."""
    from pccl_tpu.comm import (ConnectionLostError, KickedError,
                               SharedStateSyncStrategy)

    def worker(comm, rank):
        w = np.full(64, 1.0, dtype=np.float32)
        strategy = (SharedStateSyncStrategy.SEND_ONLY if rank < 2
                    else SharedStateSyncStrategy.RECEIVE_ONLY)
        _sync(comm, {"w": w}, revision=1, strategy=strategy)
        # round 2: peer 1 advances to revision 2 (content unchanged), peer 2
        # follows rx-only; peer 0 re-offers revision 1 as tx-only -> kicked
        # despite matching content
        rev = 1 if rank == 0 else 2
        info = _sync(comm, {"w": w}, revision=rev, strategy=strategy)
        return info.tx_bytes, info.rx_bytes

    results, errors = _run_peers(master.port, 3, worker)
    assert set(errors) == {0}, (results, errors)
    assert isinstance(errors[0], (KickedError, ConnectionLostError))
    # the surviving round moved no bytes: contents already matched
    assert results[1] == (0, 0) and results[2] == (0, 0)


def test_group_restart_resets_revision(master):
    """A cohort that fully disconnects and returns resumes from any revision
    (logical resume against a long-lived master) — workers restarted from an
    OLDER checkpoint must be able to sync again instead of livelocking on
    the stale expected revision."""

    def first_cohort(comm, rank):
        w = np.full(64, 1.0, dtype=np.float32)
        info = _sync(comm, {"w": w}, revision=5)
        return info.revision

    results, errors = _run_peers(master.port, 2, first_cohort)
    assert not errors, errors
    assert results == {0: 5, 1: 5}

    def restarted_cohort(comm, rank):
        # restarted from a checkpoint taken at revision 3 (< 5)
        w = np.full(64, 9.0, dtype=np.float32)
        info = _sync(comm, {"w": w}, revision=3)
        return info.revision

    results, errors = _run_peers(master.port, 2, restarted_cohort)
    assert not errors, errors
    assert results == {0: 3, 1: 3}


# ---------------------------------------------------------- peer groups


def test_peer_groups_different_keys_isolated(master):
    """Reference TestNoSyncIdenticalSharedStateMultiplePeerGroupsDifferentKeys:
    two peer groups with entirely different key sets sync concurrently and
    never interfere (no cross-group kicks, correct per-group distribution)."""

    def worker(comm, rank):
        group = rank // 2
        leader = rank % 2 == 0
        key = f"g{group}"
        w = np.full(128, float(group + 1) if leader else 0.0, dtype=np.float32)
        info = _sync(comm, {key: w}, revision=1)
        return info.tx_bytes, info.rx_bytes, float(w[0])

    results, errors = _run_peers(master.port, 4, worker,
                                 groups=[0, 0, 1, 1])
    assert not errors, errors
    for group in (0, 1):
        leader, follower = results[2 * group], results[2 * group + 1]
        # within each group exactly one full transfer in either direction
        # (1-vs-1 election tie, either may win) and both peers converge;
        # the adopted value proves no cross-group leakage
        assert leader[2] == follower[2]
        assert leader[2] in (float(group + 1), 0.0)
        assert sorted([leader[:2], follower[:2]]) == [(0, 128 * 4), (128 * 4, 0)]


def test_distributor_fanout_outdated_majority(master):
    """Reference outdated-majority scenario class
    (test_shared_state_distribution.cpp): ONE peer holds the winning
    content and FIVE peers are simultaneously outdated. The elected
    distributor serves every outdated peer's full-state fetch (fan-out is
    serial per distributor socket — this measures it instead of assuming
    it): all six converge bitwise, the distributor's tx_bytes ≈ 5x the
    state size, each outdated peer receives exactly one state's worth,
    and nobody retransmits sideways."""
    world, elems = 6, 256 * 1024
    nbytes = elems * 4

    def worker(comm, rank):
        rng = np.random.default_rng(99)  # the POPULAR content (5 agree at rev 0)
        if rank == 0:
            # the advanced peer: different content at a higher revision wins
            # the election outright (revision precedence)
            w = rng.standard_normal(elems).astype(np.float32) * 2 + 1
            info = _sync(comm, {"w": w}, revision=3)
        else:
            w = rng.standard_normal(elems).astype(np.float32)
            info = _sync(comm, {"w": w}, revision=0)
        return info.tx_bytes, info.rx_bytes, info.revision, w.tobytes()

    results, errors = _run_peers(master.port, world, worker, timeout=180)
    assert not errors, errors
    # everyone converged bitwise on the winner's content at its revision
    winner = results[0][3]
    for r in range(world):
        assert results[r][2] == 3, f"rank {r} revision {results[r][2]}"
        assert results[r][3] == winner, f"rank {r} content differs"
    # the distributor fanned the full state to each of the 5 outdated peers
    tx0, rx0 = results[0][0], results[0][1]
    assert rx0 == 0
    assert tx0 == (world - 1) * nbytes, (tx0, nbytes)
    for r in range(1, world):
        assert results[r][0] == 0, f"rank {r} sent {results[r][0]} bytes"
        assert results[r][1] == nbytes, f"rank {r} received {results[r][1]}"


# ------------------------------------------------ device-hash (TPU) entries

def test_device_hash_clean_sync_never_stages(master, monkeypatch):
    """from_jax_device entries: a clean sync (identical content everywhere)
    must move ZERO payload bytes and never stage the device array to host —
    the 8-byte on-device digest (hash type simple-tpu) decides everything.
    VERDICT r4 missing #1: the reference hashes accelerator buffers on the
    accelerator (simplehash_cuda.cu) so clean syncs never pay D2H; the
    NaN-sentinel host buffer proves the staging callback never ran."""
    monkeypatch.setenv("PCCLT_SS_HASH", "simple-tpu")

    def worker(comm, rank):
        import jax.numpy as jnp

        from pccl_tpu.comm import SharedState, TensorInfo

        arr = jnp.arange(65536 + 7, dtype=jnp.float32) * 0.5
        stats = []
        for rev in (1, 2):
            ti = TensorInfo.from_jax_device("w", arr)
            ti.data.fill(np.nan)           # sentinel: staging would clobber
            info = comm.sync_shared_state(SharedState([ti], revision=rev))
            val = ti.jax_value()
            stats.append((info.tx_bytes, info.rx_bytes, ti._updated,
                          bool(np.isnan(ti.data).all()),
                          float(np.asarray(val)[3])))
        return stats

    results, errors = _run_peers(master.port, 2, worker)
    assert not errors, errors
    for rank in (0, 1):
        for tx, rx, updated, sentinel_intact, v3 in results[rank]:
            assert (tx, rx) == (0, 0)
            assert not updated
            assert sentinel_intact, "materialize ran on a clean sync"
            assert v3 == 1.5               # jax_value = untouched device arr


# ------------------------------------------- chunk plane (docs/04)
#
# Content-addressed multi-source sync: entries split into hashed chunks,
# the master brokers a chunk map + per-key seeder sets, outdated peers
# fetch from many seeders in parallel with per-chunk verify/deadline/
# re-source, and peers that complete a key are promoted to seeders
# mid-round. The scenarios below are the churn-proof acceptance gates.


def _spawn_ss_peer(master_port, world, rank, role, tmp, keys, elems,
                   env_extra=None, revision=1, suicide_after_served=0,
                   inject_on_serve=None, linger_s=0.0, p2p_port=0,
                   ss_port=0, bench_port=0):
    import subprocess
    import sys
    result = Path(tmp) / f"peer-{rank}.json"
    env = dict(os.environ)
    env["PYTHONPATH"] = str(Path(__file__).resolve().parent.parent)
    env.setdefault("JAX_PLATFORMS", "cpu")
    if env_extra:
        env.update(env_extra)
    cmd = [sys.executable, str(Path(__file__).resolve().parent / "ss_peer.py"),
           "--master-port", str(master_port), "--world", str(world),
           "--rank", str(rank), "--role", role, "--keys", str(keys),
           "--elems", str(elems), "--revision", str(revision),
           "--result-file", str(result)]
    if suicide_after_served:
        cmd += ["--suicide-after-served", str(suicide_after_served)]
    if inject_on_serve:
        cmd += ["--inject-on-serve", inject_on_serve]
    if linger_s:
        cmd += ["--linger-s", str(linger_s)]
    if p2p_port:
        cmd += ["--p2p-port", str(p2p_port), "--ss-port", str(ss_port),
                "--bench-port", str(bench_port)]
    return subprocess.Popen(cmd, env=env), result


def test_chunk_plane_basic(master, monkeypatch):
    """Chunk-plane happy path at world=4: one advanced peer, three cold
    adopters — everyone converges bit-identically, the fetch rides chunks
    (not the legacy stream), conservation is byte-exact, and outdated
    peers that completed keys were PROMOTED to seeders mid-round."""
    monkeypatch.setenv("PCCLT_SS_CHUNK_BYTES", "131072")
    keys, elems = 4, 131072  # 4 x 512 KiB
    nbytes = keys * elems * 4

    def worker(comm, rank):
        rng = np.random.default_rng(7)
        if rank == 0:
            arrs = {f"k{i}": rng.standard_normal(elems).astype(np.float32)
                    for i in range(keys)}
            rev = 1
        else:
            arrs = {f"k{i}": np.zeros(elems, dtype=np.float32)
                    for i in range(keys)}
            rev = 0
        info = _sync(comm, arrs, revision=rev)
        return (info.tx_bytes, info.rx_bytes, info.revision,
                {k: v.tobytes() for k, v in arrs.items()},
                comm.stats()["counters"])

    results, errors = _run_peers(master.port, 4, worker)
    assert not errors, errors
    popular = results[0][3]
    served_total = 0
    for rank in range(4):
        tx, rx, rev, content, c = results[rank]
        assert rev == 1
        assert content == popular, f"rank {rank} diverged"
        served_total += c["ss_seeder_chunks_served"]
        if rank == 0:
            assert rx == 0 and c["ss_legacy_syncs"] == 0
        else:
            assert rx == nbytes
            # the transport was the chunk plane, with exact conservation
            assert c["ss_chunks_fetched"] + c["ss_chunks_resourced"] > 0
            assert (c["ss_chunk_bytes_fetched"]
                    + c["ss_chunk_bytes_resourced"]
                    - c["ss_chunk_bytes_dup"]) == nbytes
            assert c["ss_legacy_syncs"] == 0
            # every adopter announced its completed keys for mid-round
            # seeding (the promotion mechanism itself is exercised)
            assert c["ss_seeder_promotions"] == keys
    # all unique bytes came off SOMEONE's serve window
    assert served_total * 131072 >= 3 * nbytes


def test_seeder_death_failover(master, tmp_path):
    """ISSUE-13 acceptance: SIGKILL a busy seeder mid-sync at world=8 —
    every remaining peer still completes the round bit-identically, zero
    aborts/kicks, per-chunk conservation exact. The victim self-SIGKILLs
    the instant its served-chunk counter proves it is actively seeding
    the in-flight round (no orchestrator timing games)."""
    import json

    world, keys, elems = 8, 8, 65536  # 8 x 256 KiB = 2 MiB state
    nbytes = keys * elems * 4
    chunk_env = {"PCCLT_SS_CHUNK_BYTES": "131072",
                 "PCCLT_SS_FETCH_MIN_MS": "300"}
    # seeders pace their egress (wildcard ip edge: one bucket per process,
    # like a NIC) so the round is long enough that the kill is mid-round
    seeder_env = dict(chunk_env, PCCLT_WIRE_MBPS_MAP="127.0.0.1=200")
    procs = {}
    for rank in range(world):
        role = "seeder" if rank < 2 else "joiner"
        procs[rank] = _spawn_ss_peer(
            master.port, world, rank, role, tmp_path, keys, elems,
            env_extra=seeder_env if role == "seeder" else chunk_env,
            suicide_after_served=2 if rank == 1 else 0)
    deadline = time.time() + 150
    for rank, (p, _) in procs.items():
        p.wait(timeout=max(1, deadline - time.time()))
    assert procs[1][0].returncode == -9, "victim was not SIGKILLed"
    # victim writes no result by design
    assert not procs[1][1].exists()

    import ss_peer as ssp
    expected = ssp.digest_of(ssp.content_arrays(keys, elems, popular=True))
    joiner_results = []
    for rank, (p, rfile) in procs.items():
        if rank == 1:
            continue
        assert p.returncode == 0, f"rank {rank} failed rc={p.returncode}"
        res = json.loads(rfile.read_text())
        # bit-identical convergence on the popular revision, zero aborts,
        # zero kicks — the whole point of the chunk plane
        assert res["revision"] == 1
        assert res["digest"] == expected, f"rank {rank} diverged"
        c = res["counters"]
        assert c["syncs_ok"] == 1 and c["syncs_failed"] == 0
        assert c["kicked"] == 0 and c["collectives_aborted"] == 0
        if res["role"] == "joiner":
            joiner_results.append(res)
            assert res["rx_bytes"] == nbytes
            # per-chunk conservation: fetched + re-sourced - dup == unique
            assert (c["ss_chunk_bytes_fetched"] +
                    c["ss_chunk_bytes_resourced"] -
                    c["ss_chunk_bytes_dup"]) == nbytes
    assert len(joiner_results) == 6
    # at least one joiner observed the death and re-sourced around it
    assert sum(r["counters"]["ss_seeders_lost"] for r in joiner_results) >= 1


def test_chunk_blackhole_failover(master, monkeypatch):
    """ISSUE-13 acceptance: a scripted blackhole on a sync edge recovers
    via per-chunk failover INSIDE the round (re-source to another seeder),
    not by failing it."""
    from pccl_tpu.comm import netem_inject

    monkeypatch.setenv("PCCLT_SS_CHUNK_BYTES", "65536")
    monkeypatch.setenv("PCCLT_SS_FETCH_MIN_MS", "200")
    monkeypatch.setenv("PCCLT_SS_FETCH_RANGE", "2")
    keys, elems = 4, 32768  # 4 x 128 KiB
    nbytes = keys * elems * 4
    base = alloc_ports()
    p2p = {r: base + 10 + 4 * r for r in range(3)}

    def worker(comm, rank):
        rng = np.random.default_rng(5)
        if rank < 2:
            arrs = {f"k{i}": rng.standard_normal(elems).astype(np.float32)
                    for i in range(keys)}
            rev = 1
        else:
            arrs = {f"k{i}": np.zeros(elems, dtype=np.float32)
                    for i in range(keys)}
            rev = 0
            # blackhole the sync edge toward seeder rank 0 (its canonical
            # p2p endpoint — the same key the collective chaos layer uses)
            netem_inject(f"127.0.0.1:{p2p[0]}", "blackhole@t=0:1500ms")
        info = _sync(comm, arrs, revision=rev)
        return (info.rx_bytes, info.revision,
                {k: float(v.sum()) for k, v in arrs.items()},
                comm.stats()["counters"])

    # fixed p2p ports so the injection key is known up front
    from pccl_tpu.comm import Communicator
    results, errors = {}, {}

    def peer(rank):
        comm = Communicator("127.0.0.1", master.port, p2p_port=p2p[rank],
                            ss_port=base + 40 + 4 * rank,
                            bench_port=base + 52 + 4 * rank)
        try:
            comm.connect()
            deadline = time.time() + 30
            while comm.global_world_size < 3:
                if time.time() > deadline:
                    raise TimeoutError("world never formed")
                if comm.are_peers_pending():
                    comm.update_topology()
                time.sleep(0.01)
            results[rank] = worker(comm, rank)
        except Exception as e:  # noqa: BLE001
            errors[rank] = e
        finally:
            comm.destroy()

    threads = [threading.Thread(target=peer, args=(r,)) for r in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not errors, errors
    rx, rev, digest, c = results[2]
    assert rev == 1 and rx == nbytes
    assert digest == results[0][2] == results[1][2]
    # the round recovered BY re-sourcing chunks away from the blackholed
    # edge — in-round failover, not a failed sync
    assert c["syncs_ok"] >= 1 and c["syncs_failed"] == 0
    assert c["ss_chunks_resourced"] >= 1
    assert (c["ss_chunk_bytes_fetched"] + c["ss_chunk_bytes_resourced"]
            - c["ss_chunk_bytes_dup"]) == nbytes


def test_sync_edge_attribution_one_edge_per_pair(master, monkeypatch):
    """Unified-transport attribution regression: with a WILDCARD netem map
    armed (bare-ip key — netem matching cannot canonicalise endpoints for
    us) a chunk-plane sync must leave exactly ONE telemetry edge per peer
    pair, keyed by the peer's canonical p2p endpoint, with sync bytes AND
    stripe bytes metered on that same edge. The legacy serve metered
    against the fetcher's ss endpoint, minting phantom `ip:ss_port` edges
    whenever chaos/pace maps keyed by the canonical p2p endpoint."""
    monkeypatch.setenv("PCCLT_SS_CHUNK_BYTES", "131072")
    monkeypatch.setenv("PCCLT_STRIPE_CONNS", "2")
    # wildcard: every edge in the process shares the one ip bucket
    monkeypatch.setenv("PCCLT_WIRE_MBPS_MAP", "127.0.0.1=400")
    keys, elems = 4, 65536  # 4 x 256 KiB = 1 MiB, chunks of 128 KiB
    nbytes = keys * elems * 4
    base = alloc_ports()
    p2p = {r: base + 10 + 4 * r for r in range(3)}

    def worker(comm, rank):
        rng = np.random.default_rng(11)
        if rank == 0:
            arrs = {f"k{i}": rng.standard_normal(elems).astype(np.float32)
                    for i in range(keys)}
            rev = 1
        else:
            arrs = {f"k{i}": np.zeros(elems, dtype=np.float32)
                    for i in range(keys)}
            rev = 0
        info = _sync(comm, arrs, revision=rev)
        return info.revision, comm.stats()

    from pccl_tpu.comm import Communicator
    results, errors = {}, {}

    def peer(rank):
        comm = Communicator("127.0.0.1", master.port, p2p_port=p2p[rank],
                            ss_port=base + 40 + 4 * rank,
                            bench_port=base + 52 + 4 * rank)
        try:
            comm.connect()
            deadline = time.time() + 30
            while comm.global_world_size < 3:
                if time.time() > deadline:
                    raise TimeoutError("world never formed")
                if comm.are_peers_pending():
                    comm.update_topology()
                time.sleep(0.01)
            results[rank] = worker(comm, rank)
        except Exception as e:  # noqa: BLE001
            errors[rank] = e
        finally:
            comm.destroy()

    threads = [threading.Thread(target=peer, args=(r,)) for r in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not errors, errors

    tx_sync = tx_stripe = rx_sync = 0
    for rank in range(3):
        rev, stats = results[rank]
        assert rev == 1
        assert stats["counters"]["ss_legacy_syncs"] == 0
        edges = stats["edges"]
        # ONE edge per peer pair, keyed by the canonical p2p endpoint —
        # no ss-port phantoms, nothing keyed by an ephemeral source port
        expected = {f"127.0.0.1:{p2p[r]}" for r in range(3) if r != rank}
        assert set(edges) == expected, f"rank {rank}: {sorted(edges)}"
        for e in edges.values():
            tx_sync += e["tx_sync_bytes"]
            tx_stripe += e["tx_stripe_bytes"]
            rx_sync += e["rx_sync_bytes"]
    # both cold joiners' unique payload was metered on canonical edges,
    # and every chunk serve rode the striped window path (>= 128 KiB
    # ranges at PCCLT_STRIPE_CONNS=2) — chunk bytes visible in stripe
    # counters is the ISSUE-19 acceptance signal
    assert tx_sync >= 2 * nbytes
    assert rx_sync >= 2 * nbytes
    assert tx_stripe >= 2 * nbytes


@pytest.mark.slow
def test_swarm_world16_chaos_relay_gate(master, tmp_path):
    """ISSUE-19 acceptance: world=16 (8 seeders + 8 cold joiners) under a
    PCCLT_WIRE_CHAOS_MAP seeder-edge blackhole AND a busiest-seeder
    SIGKILL. The blackhole is re-armed mid-serve (netem_inject on the
    map-created edge, triggered by the seeder's own pre-send serve
    accounting) so the paced in-flight window stalls and the serve-side
    watchdog climbs the full ladder: SUSPECT fresh-conn reissue, then
    CONFIRMED relay detour via a third peer. Gates: zero failed syncs,
    bit-identical state, >= 1 chunk delivered via the relay detour,
    per-chunk conservation byte-exact."""
    import json

    world, keys, elems = 16, 2, 1048576  # 2 x 4 MiB = 8 MiB state
    nbytes = keys * elems * 4
    base = alloc_ports(160)
    p2p = {r: base + 8 + r for r in range(world)}
    dark_ep = f"127.0.0.1:{p2p[8]}"  # seeder0 -> joiner8: the dark edge

    common = {"PCCLT_SS_CHUNK_BYTES": "131072",
              "PCCLT_SS_FETCH_RANGE": "8",     # 1 MiB ranges
              "PCCLT_SS_FETCH_MIN_MS": "400",
              "PCCLT_WATCHDOG": "1",
              "PCCLT_WATCHDOG_MIN_MS": "150"}
    # rank 0: the edge toward joiner 8 is exact-listed (map-created at dial
    # time, so live conns hold it and the mid-run inject arms THE edge they
    # pace on) and slowed to 60 Mbit so a 1 MiB serve window is in flight
    # long enough for the injected outage to land under it
    dark_seeder = dict(common)
    dark_seeder["PCCLT_WIRE_MBPS_MAP"] = f"{dark_ep}=60"
    dark_seeder["PCCLT_WIRE_CHAOS_MAP"] = f"{dark_ep}=blackhole@t=0:500ms"

    procs = {}
    for rank in range(world):
        role = "seeder" if rank < 8 else "joiner"
        kw = {}
        if rank == 0:
            kw["env_extra"] = dark_seeder
            kw["inject_on_serve"] = f"{dark_ep}=blackhole@t=0:8000ms"
        else:
            kw["env_extra"] = common
        if rank == 1:
            kw["suicide_after_served"] = 4
        procs[rank] = _spawn_ss_peer(
            master.port, world, rank, role, tmp_path, keys, elems,
            linger_s=8.0, p2p_port=p2p[rank], ss_port=base + 40 + rank,
            bench_port=base + 72 + rank, **kw)

    deadline = time.time() + 300
    for rank, (p, _) in procs.items():
        p.wait(timeout=max(1, deadline - time.time()))
    assert procs[1][0].returncode == -9, "victim was not SIGKILLed"
    assert not procs[1][1].exists()

    import ss_peer as ssp
    expected = ssp.digest_of(ssp.content_arrays(keys, elems, popular=True))
    res = {}
    for rank, (p, rfile) in procs.items():
        if rank == 1:
            continue
        assert p.returncode == 0, f"rank {rank} failed rc={p.returncode}"
        res[rank] = json.loads(rfile.read_text())
        r = res[rank]
        # bit-identical convergence, zero failed syncs, zero aborts/kicks
        assert r["revision"] == 1
        assert r["digest"] == expected, f"rank {rank} diverged"
        c = r["counters"]
        assert c["syncs_ok"] == 1 and c["syncs_failed"] == 0
        assert c["kicked"] == 0 and c["collectives_aborted"] == 0
        if r["role"] == "joiner":
            assert r["rx_bytes"] == nbytes
            # conservation byte-exact: fetched + re-sourced - dup == unique,
            # and unique + delta-skipped == total (cold joiner: delta == 0)
            assert (c["ss_chunk_bytes_fetched"] +
                    c["ss_chunk_bytes_resourced"] -
                    c["ss_chunk_bytes_dup"]) == nbytes
            assert c["ss_chunk_bytes_delta_skipped"] == 0
    # the SIGKILLed seeder was observed and re-sourced around
    joiners = [res[r] for r in range(8, world)]
    assert sum(r["counters"]["ss_seeders_lost"] for r in joiners) >= 1
    # >= 1 chunk delivered via the relay detour: the dark seeder's ladder
    # CONFIRMED the edge and detoured its backlog via a third peer...
    s0 = res[0]["edges"]
    assert sum(e["wd_relays"] for e in s0.values()) >= 1, s0
    assert sum(e["wd_confirms"] for e in s0.values()) >= 1
    # ...and the detoured window landed at the joiner, charged to the
    # origin seeder's canonical edge
    j8 = res[8]["edges"]
    assert sum(e["rx_relay_bytes"] for e in j8.values()) >= 1, j8
    # the injected fault actually gated live traffic
    assert res[0]["counters"]["chaos_faults_activated"] >= 1


@pytest.mark.slow
def test_swarm_cold_joiners_beat_single_seeder(master, tmp_path):
    """ISSUE-13 acceptance (test twin of the sync_swarm_speedup bench):
    4 simultaneous cold joiners at world=8 complete sync measurably
    faster on the chunk plane than on the forced single-seeder baseline."""
    import json

    keys, elems = 8, 262144  # 8 MiB state
    pace = {"PCCLT_WIRE_MBPS_MAP": "127.0.0.1=250"}

    def leg(tmp, chunked):
        env = dict(pace)
        env["PCCLT_SS_CHUNK_BYTES"] = "262144" if chunked else "0"
        procs = {}
        for rank in range(8):
            role = "seeder" if rank < 4 else "joiner"
            procs[rank] = _spawn_ss_peer(
                master.port, 8, rank, role, tmp, keys, elems, env_extra=env)
        for rank, (p, _) in procs.items():
            p.wait(timeout=240)
            assert p.returncode == 0, f"rank {rank} rc={p.returncode}"
        walls = []
        for rank, (_, rfile) in procs.items():
            res = json.loads(rfile.read_text())
            if res["role"] == "joiner":
                walls.append(res["sync_wall_s"])
        return max(walls)

    d1 = tmp_path / "chunked"
    d2 = tmp_path / "legacy"
    d1.mkdir()
    d2.mkdir()
    chunked = leg(d1, chunked=True)
    legacy = leg(d2, chunked=False)
    assert legacy / chunked >= 1.5, (legacy, chunked)


def test_device_hash_divergent_peer_syncs(master, monkeypatch):
    """One diverging peer among three: the popular side wins, the elected
    distributor lazily MATERIALIZES its device array (exactly one peer
    reports tx>0), the outdated peer receives into its host buffer and
    jax_value() returns the popular content."""
    monkeypatch.setenv("PCCLT_SS_HASH", "simple-tpu")
    n = 32768

    def worker(comm, rank):
        import jax.numpy as jnp

        from pccl_tpu.comm import SharedState, TensorInfo

        arr = jnp.full(n, 3.0 if rank == 2 else 42.0, dtype=jnp.float32)
        ti = TensorInfo.from_jax_device("w", arr)
        if rank == 2:
            ti.data.fill(np.nan)
        info = comm.sync_shared_state(SharedState([ti], revision=1))
        val = np.asarray(ti.jax_value())
        return info.tx_bytes, info.rx_bytes, ti._updated, float(val[0])

    results, errors = _run_peers(master.port, 3, worker)
    assert not errors, errors
    assert all(r[3] == 42.0 for r in results.values())  # converged on popular
    assert results[2][2] and results[2][1] == n * 4     # outdated peer rx
    servers = [r for r in (0, 1) if results[r][0] == n * 4]
    assert len(servers) == 1, results                   # exactly one served
    assert not results[0][2] and not results[1][2]
