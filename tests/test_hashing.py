"""Hash bit-parity: native simplehash == Python twin; native CRC32 == zlib.

Reference parity: the reference tests its CPU simplehash against the real
CUDA kernel digest (simplehash_cpu_test.cu) and CRC32 against a reference
implementation on randomized buffers (crc32_cpu_test.cpp) — the invariant
under test is device/implementation-independent digests (SURVEY.md §2 #13/#14).
"""

from __future__ import annotations

import ctypes
import zlib
from pathlib import Path

import numpy as np
import pytest

from pccl_tpu.ops import hashing

LIB = Path(__file__).resolve().parent.parent / "pccl_tpu" / "native" / "build" / "libpcclt.so"
needs_native = pytest.mark.skipif(not LIB.exists(), reason="native lib not built")


def _native_hash(hash_type: int, data: bytes) -> int:
    from pccl_tpu.comm import _native

    lib = _native.load()
    buf = (ctypes.c_char * len(data)).from_buffer_copy(data) if data else None
    return int(lib.pccltHashBuffer(hash_type, buf, len(data)))


@needs_native
@pytest.mark.parametrize("n", [0, 1, 3, 4, 5, 255, 256, 1024, 1027,
                               256 * 4, 256 * 4 * 3 + 7, 1 << 16])
def test_simplehash_python_twin_matches_native(n):
    rng = np.random.RandomState(n)
    data = rng.bytes(n)
    assert hashing.simplehash(data) == _native_hash(0, data)


@needs_native
def test_simplehash_on_ndarray_matches_native():
    rng = np.random.RandomState(7)
    arr = rng.randn(1000).astype(np.float32)
    assert hashing.simplehash(arr) == _native_hash(0, arr.tobytes())


@needs_native
@pytest.mark.parametrize("n", [0, 1, 9, 4096, 65537])
def test_crc32_matches_zlib(n):
    rng = np.random.RandomState(n)
    data = rng.bytes(n)
    assert _native_hash(1, data) == zlib.crc32(data)


@needs_native
def test_crc32_known_vector():
    # the canonical CRC-32/IEEE check value
    assert _native_hash(1, b"123456789") == 0xCBF43926


def test_simplehash_sensitivity():
    base = b"x" * 1024
    h0 = hashing.simplehash(base)
    flipped = bytearray(base)
    flipped[512] ^= 1
    assert hashing.simplehash(bytes(flipped)) != h0
    assert hashing.simplehash(base + b"\x00") != h0  # length-extension differs


@needs_native
def test_shared_state_sync_with_crc32(monkeypatch):
    """Shared-state drift detection must work end-to-end with the alternate
    CRC32 hash type (PCCLT_SS_HASH=crc32, read per hash call)."""
    import threading
    import time

    monkeypatch.setenv("PCCLT_SS_HASH", "crc32")
    from pccl_tpu.comm import (MasterNode, Communicator, SharedState,
                               SharedStateSyncStrategy, TensorInfo)

    from conftest import alloc_ports

    ports = alloc_ports(64)
    master = MasterNode("0.0.0.0", ports)
    master.run()
    errors = []

    def worker(rank):
        try:
            base = ports + 8 + rank * 16
            comm = Communicator("127.0.0.1", master.port, p2p_port=base,
                                ss_port=base + 4, bench_port=base + 8)
            comm.connect()
            deadline = time.time() + 30
            while comm.world_size < 2:
                if time.time() > deadline:
                    raise TimeoutError("world never reached 2")
                if comm.are_peers_pending():
                    comm.update_topology()
                time.sleep(0.01)
            w = np.full(256, 5.0 if rank == 0 else 0.0, dtype=np.float32)
            state = SharedState([TensorInfo.from_numpy("w", w)], revision=1)
            comm.sync_shared_state(
                state,
                SharedStateSyncStrategy.SEND_ONLY if rank == 0
                else SharedStateSyncStrategy.RECEIVE_ONLY)
            np.testing.assert_allclose(w, np.full(256, 5.0))
            comm.destroy()
        except Exception as e:  # noqa: BLE001
            errors.append((rank, e))

    ts = [threading.Thread(target=worker, args=(r,)) for r in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=120)
    # a hung worker must fail loudly, not pass with empty `errors` while
    # racing monkeypatch's env teardown against in-flight getenv calls
    stuck = [t for t in ts if t.is_alive()]
    master.interrupt()
    master.destroy()
    assert not stuck, "worker threads hung"
    assert not errors, f"peer failures: {errors}"


def test_jax_simplehash_layout_independent(eight_devices):
    """A sharded and a replicated jax array with the same content must hash
    identically (the device-independence invariant)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from pccl_tpu.parallel import mesh as mesh_lib

    x = np.arange(1024, dtype=np.float32)
    mesh = mesh_lib.make_mesh(eight_devices, ("dp",), (8,))
    sharded = jax.device_put(x, NamedSharding(mesh, P("dp")))
    replicated = jax.device_put(x, NamedSharding(mesh, P()))
    h_host = hashing.simplehash(x)
    assert hashing.jax_simplehash(sharded) == h_host
    assert hashing.jax_simplehash(replicated) == h_host


def test_simplehash_tpu_numpy_vs_native():
    """The TPU-native hash (type 2) must be bit-identical between the
    numpy twin and the C++ core (pccltHashBuffer hash_type=2) across
    sizes that cover: sub-row, exact row, multi-row, partial tail word."""
    from pccl_tpu.comm import _native
    from pccl_tpu.ops import hashing

    lib = _native.load()
    rng = np.random.default_rng(5)
    for nbytes in (0, 1, 3, 4, 17, 4096, 65536 * 4, 65536 * 4 + 4,
                   65536 * 8 + 7, 1 << 20):
        buf = rng.integers(0, 256, nbytes, dtype=np.uint8).tobytes()
        h_py = hashing.simplehash_tpu(buf)
        h_c = lib.pccltHashBuffer(2, buf, len(buf))
        assert h_py == h_c, f"nbytes={nbytes}: {h_py:#x} != {h_c:#x}"


def test_simplehash_tpu_device_parity():
    """jax_simplehash_device (the on-device digest — only 8 bytes cross
    to the host) must equal simplehash_tpu of the same canonical bytes
    for every supported itemsize, including odd counts needing padding.
    VERDICT r4 missing #1: the reference hashes accelerator state on the
    accelerator (simplehash_cuda.cu) so a clean sync never pays D2H."""
    import jax
    import jax.numpy as jnp

    from pccl_tpu.ops import hashing

    key = jax.random.PRNGKey(0)
    cases = [
        jax.random.normal(key, (1000,), jnp.float32),
        jax.random.normal(key, (64, 129), jnp.bfloat16),
        jax.random.normal(key, (33,), jnp.float16),     # odd 2-byte count
        jnp.arange(70000, dtype=jnp.int32),             # > one lane row
        jnp.arange(255, dtype=jnp.uint8),               # 1-byte, pad to u32
        jax.random.randint(key, (131072 + 3,), 0, 127, jnp.int8),
        jnp.zeros((0,), jnp.float32),           # empty: rows=0 twin parity
    ]
    for arr in cases:
        host = np.asarray(arr)
        assert hashing.jax_simplehash_device(arr) == \
            hashing.simplehash_tpu(host), (arr.dtype, arr.shape)


def test_simplehash_tpu_native_env_dispatch():
    """PCCLT_SS_HASH=simple-tpu must route content_hash to the new type
    (checked via pccltHashBuffer equivalence of types 0 vs 2 differing)."""
    from pccl_tpu.comm import _native
    from pccl_tpu.ops import hashing

    lib = _native.load()
    buf = b"pccl-tpu-hash-dispatch"
    assert lib.pccltHashBuffer(2, buf, len(buf)) == \
        hashing.simplehash_tpu(buf)
    assert lib.pccltHashBuffer(0, buf, len(buf)) == hashing.simplehash(buf)
    assert lib.pccltHashBuffer(0, buf, len(buf)) != \
        lib.pccltHashBuffer(2, buf, len(buf))


def test_simplehash_tpu_uniform_content_distinguishes():
    """Regression: constant-valued arrays (zero-init params are exactly
    this) must produce distinct digests per value — the first fold design
    cancelled structurally on identical lanes and hashed EVERY constant
    array to the same value."""
    from pccl_tpu.ops import hashing

    digests = {hashing.simplehash_tpu(np.full(32768, v, np.float32))
               for v in (0.0, 1.0, 3.0, 42.0)}
    assert len(digests) == 4, digests
    # single-bit flip anywhere must change the digest
    base = np.zeros(100000, np.uint8)
    h0 = hashing.simplehash_tpu(base)
    for pos in (0, 1, 65535, 65536, 99999):
        flip = base.copy()
        flip[pos] = 1
        assert hashing.simplehash_tpu(flip) != h0, pos
