"""Topology optimization e2e: bandwidth probes -> ATSP ring -> moonshot.

Reference parity: pcclOptimizeTopology flow (SURVEY.md §3.4) — clients vote,
master hands out missing bandwidth-benchmark edges, clients flood-probe each
other's benchmark servers, master solves the ATSP and distributes the
optimized ring; a second call can adopt the asynchronously-improved
"moonshot" solution (ccoip_master_handler.cpp:455-496).
"""

from __future__ import annotations

import threading
import time
from pathlib import Path

import numpy as np
import pytest

LIB = Path(__file__).resolve().parent.parent / "pccl_tpu" / "native" / "build" / "libpcclt.so"
pytestmark = pytest.mark.skipif(not LIB.exists(), reason="native lib not built")


def test_optimize_topology_three_peers(monkeypatch):
    monkeypatch.setenv("PCCLT_BENCH_SECONDS", "0.2")  # short probes
    monkeypatch.setenv("PCCLT_MOONSHOT_MS", "300")
    from pccl_tpu.comm import Communicator, MasterNode, ReduceOp

    from conftest import alloc_ports

    ports = alloc_ports(64)
    master = MasterNode("0.0.0.0", ports)
    master.run()
    errors = []
    done = []

    def worker(rank):
        try:
            base = ports + 8 + rank * 16
            comm = Communicator("127.0.0.1", master.port, p2p_port=base,
                                ss_port=base + 4, bench_port=base + 8)
            comm.connect()
            deadline = time.time() + 30
            while comm.world_size < 3:
                if time.time() > deadline:
                    raise TimeoutError("world never reached 3")
                if comm.are_peers_pending():
                    comm.update_topology()
                time.sleep(0.01)

            comm.optimize_topology()          # probes + quick ATSP
            # the ring must still carry collectives correctly
            x = np.ones(1024, dtype=np.float32)
            y = np.empty_like(x)
            info = comm.all_reduce(x, y, op=ReduceOp.SUM)
            assert info.world_size == 3 and y[0] == 3.0
            time.sleep(0.6)                   # let the moonshot finish
            comm.optimize_topology()          # may adopt the moonshot ring
            info = comm.all_reduce(x, y, op=ReduceOp.SUM, tag=1)
            assert info.world_size == 3 and y[0] == 3.0
            done.append(rank)
            comm.destroy()
        except Exception as e:  # noqa: BLE001
            errors.append((rank, e))

    ts = [threading.Thread(target=worker, args=(r,)) for r in range(3)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=180)
    stuck = [t for t in ts if t.is_alive()]
    master.interrupt()
    master.destroy()
    assert not stuck, "worker threads hung"
    assert not errors, f"peer failures: {errors}"
    assert sorted(done) == [0, 1, 2]


def test_optimize_survives_peer_departure(monkeypatch):
    """Optimize-protocol failure path (reference exercises this surface in
    ccoip_master_handler.cpp:392-563): a peer leaves BETWEEN the optimize
    votes — the master's disconnect recheck must complete the round with
    the survivors instead of waiting forever for the missing vote, and the
    adopted ring must still carry collectives."""
    monkeypatch.setenv("PCCLT_BENCH_SECONDS", "0.5")  # measurable probe window
    from pccl_tpu.comm import Communicator, MasterNode, ReduceOp

    from conftest import alloc_ports

    ports = alloc_ports(96)
    master = MasterNode("0.0.0.0", ports)
    master.run()
    errors = []
    done = []

    def worker(rank):
        try:
            base = ports + 8 + rank * 16
            comm = Communicator("127.0.0.1", master.port, p2p_port=base,
                                ss_port=base + 4, bench_port=base + 8)
            comm.connect()
            deadline = time.time() + 30
            while comm.world_size < 4:
                if time.time() > deadline:
                    raise TimeoutError("world never reached 4")
                if comm.are_peers_pending():
                    comm.update_topology()
                time.sleep(0.01)

            if rank == 3:
                # deserter: never votes optimize, leaves while the others'
                # votes are parked at the master
                time.sleep(1.0)
                comm.destroy()
                done.append(rank)
                return
            comm.optimize_topology()  # blocks on rank 3's vote until it dies
            x = np.ones(512, dtype=np.float32)
            info = comm.all_reduce(x, op=ReduceOp.SUM)
            assert info.world_size == 3 and x[0] == 3.0
            done.append(rank)
            comm.destroy()
        except Exception as e:  # noqa: BLE001
            errors.append((rank, e))

    ts = [threading.Thread(target=worker, args=(r,), daemon=True)
          for r in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=180)
    stuck = [t for t in ts if t.is_alive()]
    master.interrupt()
    master.destroy()
    assert not stuck, "worker threads hung"
    assert not errors, f"peer failures: {errors}"
    assert sorted(done) == [0, 1, 2, 3]


def test_optimize_isolated_from_garbage_reports(monkeypatch):
    """Bad-estimate robustness through the real protocol: a pending client
    (joined, never admitted) floods the master with bandwidth reports —
    NaN, inf, zero, negative, unknown target uuids. None of it may poison
    or wedge the accepted group's optimize round, and the master must stay
    alive throughout."""
    import math
    import socket
    import struct

    monkeypatch.setenv("PCCLT_BENCH_SECONDS", "0.2")
    from pccl_tpu.comm import Communicator, MasterNode, ReduceOp

    from conftest import alloc_ports

    ports = alloc_ports(96)
    master = MasterNode("0.0.0.0", ports)
    master.run()

    def frame(ptype, payload=b""):
        return struct.pack(">IH", 2 + len(payload), ptype) + payload

    def hello(peer_group):
        # HelloC2M: wire_rev u8, peer_group u32, 3x u16 ports, str adv_ip
        return (struct.pack(">BIHHH", 2, peer_group, 1, 2, 3) +
                struct.pack(">I", 0))

    errors = []
    done = []

    def worker(rank):
        try:
            base = ports + 8 + rank * 16
            comm = Communicator("127.0.0.1", master.port, p2p_port=base,
                                ss_port=base + 4, bench_port=base + 8)
            comm.connect()
            deadline = time.time() + 30
            while comm.world_size < 3:
                if time.time() > deadline:
                    raise TimeoutError("world never reached 3")
                if comm.are_peers_pending():
                    comm.update_topology()
                time.sleep(0.01)
            barrier.wait(timeout=30)  # 1: formation done — garbage may join
            barrier.wait(timeout=30)  # 2: garbage landed — optimize now
            comm.optimize_topology()
            x = np.ones(256, dtype=np.float32)
            info = comm.all_reduce(x, op=ReduceOp.SUM)
            assert info.world_size == 3 and x[0] == 3.0
            done.append(rank)
            comm.destroy()
        except Exception as e:  # noqa: BLE001
            errors.append((rank, e))

    barrier = threading.Barrier(4)
    ts = [threading.Thread(target=worker, args=(r,), daemon=True)
          for r in range(3)]
    for t in ts:
        t.start()

    # the garbage client joins AFTER formation completes (a hello racing the
    # formation votes would be admitted into the establish round and wedge
    # it); post-formation nobody votes topology, so it stays pending — and a
    # pending client's reports must not poison the accepted group.
    # try/finally: a worker failing before its barrier breaks the barrier —
    # teardown must still run and the WORKER's error must surface, not the
    # main thread's BrokenBarrierError.
    try:
        barrier.wait(timeout=60)  # 1: workers formed their world
        with socket.create_connection(("127.0.0.1", master.port),
                                      timeout=10) as s:
            s.sendall(frame(0x1001, hello(peer_group=7)))
            time.sleep(0.3)  # welcome lands; we ignore it
            for mbps in (float("nan"), float("inf"), -float("inf"), 0.0, -1.0,
                         1e308, 5e-324):
                payload = bytes(range(16)) + struct.pack(">d", mbps)
                s.sendall(frame(0x100A, payload))
            # truncated report (uuid only) for good measure
            s.sendall(frame(0x100A, bytes(16)))
            time.sleep(0.2)
            barrier.wait(timeout=30)  # 2: release the workers to optimize
            for t in ts:
                t.join(timeout=120)
    except threading.BrokenBarrierError:
        pass  # a worker died early; its exception is in `errors`
    finally:
        stuck = [t for t in ts if t.is_alive()]
        master.interrupt()
        master.destroy()
    assert not errors, f"peer failures: {errors}"
    assert not stuck, "worker threads hung"
    assert sorted(done) == [0, 1, 2]
