"""Topology optimization e2e: bandwidth probes -> ATSP ring -> moonshot.

Reference parity: pcclOptimizeTopology flow (SURVEY.md §3.4) — clients vote,
master hands out missing bandwidth-benchmark edges, clients flood-probe each
other's benchmark servers, master solves the ATSP and distributes the
optimized ring; a second call can adopt the asynchronously-improved
"moonshot" solution (ccoip_master_handler.cpp:455-496).
"""

from __future__ import annotations

import os
import threading
import time
from pathlib import Path

import numpy as np
import pytest

LIB = Path(__file__).resolve().parent.parent / "pccl_tpu" / "native" / "build" / "libpcclt.so"
pytestmark = pytest.mark.skipif(not LIB.exists(), reason="native lib not built")


def test_optimize_topology_three_peers(monkeypatch):
    monkeypatch.setenv("PCCLT_BENCH_SECONDS", "0.2")  # short probes
    monkeypatch.setenv("PCCLT_MOONSHOT_MS", "300")
    from pccl_tpu.comm import Communicator, MasterNode, ReduceOp

    from conftest import alloc_ports

    ports = alloc_ports(64)
    master = MasterNode("0.0.0.0", ports)
    master.run()
    errors = []
    done = []

    def worker(rank):
        try:
            base = ports + 8 + rank * 16
            comm = Communicator("127.0.0.1", master.port, p2p_port=base,
                                ss_port=base + 4, bench_port=base + 8)
            comm.connect()
            deadline = time.time() + 30
            while comm.world_size < 3:
                if time.time() > deadline:
                    raise TimeoutError("world never reached 3")
                if comm.are_peers_pending():
                    comm.update_topology()
                time.sleep(0.01)

            comm.optimize_topology()          # probes + quick ATSP
            # the ring must still carry collectives correctly
            x = np.ones(1024, dtype=np.float32)
            y = np.empty_like(x)
            info = comm.all_reduce(x, y, op=ReduceOp.SUM)
            assert info.world_size == 3 and y[0] == 3.0
            time.sleep(0.6)                   # let the moonshot finish
            comm.optimize_topology()          # may adopt the moonshot ring
            info = comm.all_reduce(x, y, op=ReduceOp.SUM, tag=1)
            assert info.world_size == 3 and y[0] == 3.0
            done.append(rank)
            comm.destroy()
        except Exception as e:  # noqa: BLE001
            errors.append((rank, e))

    ts = [threading.Thread(target=worker, args=(r,)) for r in range(3)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=180)
    stuck = [t for t in ts if t.is_alive()]
    master.interrupt()
    master.destroy()
    assert not stuck, "worker threads hung"
    assert not errors, f"peer failures: {errors}"
    assert sorted(done) == [0, 1, 2]
