"""Observability test peer (subprocess worker).

One peer of a wire_topology/netem-emulated loopback world with the fleet
observability plane on: applies its per-rank env (wire maps, telemetry
cadence) BEFORE touching the native layer, optionally runs an
optimize_topology round (fills the master's bandwidth matrix), runs a few
fp32 ring all-reduces, then prints one JSON line with its stats()
snapshot. ``--hold`` keeps the peer alive (digests still flowing) until a
line arrives on stdin — the orchestrating test scrapes the master's
/metrics and /health mid-run against live peers, then releases them.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--master-port", type=int, required=True)
    ap.add_argument("--rank", type=int, required=True)
    ap.add_argument("--world", type=int, required=True)
    ap.add_argument("--port-base", type=int, required=True)
    ap.add_argument("--count", type=int, default=1 << 18)
    ap.add_argument("--iters", type=int, default=3)
    ap.add_argument("--push-ms", type=int, default=150)
    ap.add_argument("--optimize", action="store_true",
                    help="run an optimize_topology round first (fills the "
                         "bandwidth matrix the straggler detector compares "
                         "against)")
    ap.add_argument("--hold", action="store_true",
                    help="after printing stats, stay connected (digests "
                         "keep flowing) until a line arrives on stdin")
    ap.add_argument("--trace-out", default=None,
                    help="dump this peer's native Chrome trace here at the "
                         "end (tools/trace_merge input)")
    ap.add_argument("--env", default="{}",
                    help="JSON env dict applied before the native load")
    args = ap.parse_args()

    os.environ.update(json.loads(args.env))
    os.environ["PCCLT_TELEMETRY_PUSH_MS"] = str(args.push_ms)

    import numpy as np

    from pccl_tpu.comm import Communicator, ReduceOp, trace_dump, trace_enable
    from pccl_tpu.comm.native_bench import _rank_ports

    trace_enable(True)
    p2p, ss, bench = _rank_ports(args.port_base, args.rank)
    comm = Communicator("127.0.0.1", args.master_port,
                        p2p_port=p2p, ss_port=ss, bench_port=bench)
    comm.connect()
    deadline = time.time() + 60
    while comm.world_size < args.world:
        if time.time() > deadline:
            print(json.dumps({"rank": args.rank, "error": "world timeout"}),
                  flush=True)
            return 2
        if comm.are_peers_pending():
            comm.update_topology()
        time.sleep(0.02)

    if args.optimize:
        comm.optimize_topology()

    x = np.full(args.count, float(args.rank + 1), dtype=np.float32)
    t0 = time.perf_counter()
    for _ in range(args.iters):
        y = x.copy()
        comm.all_reduce(y, op=ReduceOp.SUM, tag=0)
        expect = args.world * (args.world + 1) / 2
        if float(y[0]) != expect or float(y[-1]) != expect:
            print(json.dumps({"rank": args.rank,
                              "error": f"bad result {y[0]} != {expect}"}),
                  flush=True)
            return 3
    elapsed = time.perf_counter() - t0

    # sit out at least two push intervals so a digest covering the final
    # op's bytes reaches the master before the test scrapes
    time.sleep(max(0.3, 2.5 * args.push_ms / 1000.0))
    print(json.dumps({"rank": args.rank, "stats": comm.stats(),
                      "elapsed_s": elapsed}), flush=True)
    if args.hold:
        sys.stdin.readline()
    if args.trace_out:
        trace_dump(args.trace_out)
    comm.destroy()
    return 0


if __name__ == "__main__":
    sys.exit(main())
