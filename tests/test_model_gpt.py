import jax
import jax.numpy as jnp
import numpy as np

from pccl_tpu.models import gpt


def test_forward_shapes():
    cfg = gpt.tiny_config()
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    tokens = jnp.zeros((2, 16), dtype=jnp.int32)
    logits = gpt.forward_jit(params, tokens, cfg)
    assert logits.shape == (2, 16, cfg.vocab_size)
    assert logits.dtype == jnp.float32
    assert np.isfinite(np.asarray(logits)).all()


def test_loss_decreases_one_sgd_step():
    cfg = gpt.tiny_config()
    params = gpt.init_params(jax.random.PRNGKey(1), cfg)
    key = jax.random.PRNGKey(2)
    tokens = jax.random.randint(key, (4, 32), 0, cfg.vocab_size)
    targets = jnp.roll(tokens, -1, axis=1)

    loss0, grads = jax.value_and_grad(gpt.loss_fn)(params, tokens, targets, cfg)
    params2 = jax.tree.map(lambda p, g: p - 0.1 * g, params, grads)
    loss1 = gpt.loss_fn(params2, tokens, targets, cfg)
    assert float(loss1) < float(loss0)


def test_causality():
    """Changing a future token must not change past logits."""
    cfg = gpt.tiny_config()
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    t1 = jnp.zeros((1, 8), dtype=jnp.int32)
    t2 = t1.at[0, 7].set(3)
    l1 = gpt.forward(params, t1, cfg)
    l2 = gpt.forward(params, t2, cfg)
    np.testing.assert_allclose(np.asarray(l1[0, :7]), np.asarray(l2[0, :7]), atol=1e-5)


def test_graft_entry_and_dryrun(eight_devices):
    import __graft_entry__ as ge

    fn, args = ge.entry()
    out = jax.jit(fn)(*args)
    assert out.shape[0] == 2
    ge.dryrun_multichip(8)


def test_named_configs():
    """Preset ladder: GPT-2 124M dims and MXU-padded vocab; overrides win."""
    c = gpt.named_config("gpt2")
    assert (c.n_layer, c.n_head, c.n_embd, c.block_size) == (12, 12, 768, 1024)
    assert c.vocab_size % 64 == 0  # padded for MXU-friendly embed matmuls
    c2 = gpt.named_config("gpt2", block_size=256, vocab_size=256)
    assert c2.block_size == 256 and c2.vocab_size == 256
    assert set(gpt.PRESETS) >= {"tiny", "gpt2", "gpt2-medium", "gpt2-large",
                                "gpt2-xl"}


def test_profiler_sections():
    from pccl_tpu.utils.profiler import Profiler

    prof = Profiler()
    with prof.section("a"):
        with prof.section("b"):
            pass
    with prof.section("a"):
        pass
    stats = prof.stats()
    assert stats["a"].count == 2 and stats["b"].count == 1
    table = prof.summary()
    assert "a" in table and "mean_ms" in table
    import json as _json
    import tempfile

    with tempfile.NamedTemporaryFile(suffix=".json", mode="r") as f:
        prof.export_chrome_trace(f.name)
        trace = _json.load(open(f.name))
    assert len(trace["traceEvents"]) == 3
    prof.reset()
    assert prof.stats() == {}


def test_remat_modes_match_no_remat():
    """Both checkpointing modes (full remat, "dots" policy) are pure
    memory/recompute trades — loss AND grads must match the stash-everything
    path (same ops, re-executed; CPU fp is deterministic)."""
    import jax
    import numpy as np

    from pccl_tpu.models import gpt

    # n_layer=4: "sqrt" groups as G=2 — L=2 would degenerate to G=1 and
    # silently skip the grouped two-level path this test must cover
    cfg = gpt.tiny_config(n_layer=4)
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    tok = jax.random.randint(jax.random.PRNGKey(1), (2, cfg.block_size), 0,
                             cfg.vocab_size)

    def lg(remat):
        return jax.jit(jax.value_and_grad(
            lambda p: gpt.loss_fn(p, tok, tok, cfg, None, remat)))(params)

    l0, g0 = lg(False)
    for mode in (True, "dots", "sqrt"):
        l1, g1 = lg(mode)
        np.testing.assert_allclose(float(l1), float(l0), rtol=1e-6)
        jax.tree.map(lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6), g0, g1)


def test_chunked_ce_matches_full():
    """loss_chunk is a pure memory/recompute trade: the chunked
    (scan + checkpoint) CE must match the full-logits path in loss AND
    grads for both the tied and untied head (same matmuls re-executed;
    CPU fp is deterministic up to reduction order, hence the tolerances)."""
    import jax
    import numpy as np

    from pccl_tpu.models import gpt

    for untie in (False, True):
        cfg = gpt.tiny_config(untie_head=untie)
        params = gpt.init_params(jax.random.PRNGKey(0), cfg)
        tok = jax.random.randint(jax.random.PRNGKey(1), (2, cfg.block_size),
                                 0, cfg.vocab_size)

        def lg(chunk):
            return jax.jit(jax.value_and_grad(
                lambda p: gpt.loss_fn(p, tok, tok, cfg, None, False,
                                      chunk)))(params)

        l0, g0 = lg(None)
        l1, g1 = lg(cfg.block_size // 4)
        np.testing.assert_allclose(float(l1), float(l0), rtol=2e-5)
        # non-head leaves come out bit-identical; the head grad differs by
        # bf16 accumulation order (chunked partial sums vs one big matmul),
        # measured maxabs ~1e-4 on grads of magnitude ~0.03
        jax.tree.map(lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-2, atol=5e-4), g0, g1)


def test_loss_chunk_must_divide():
    """A non-dividing loss_chunk raises immediately — a silent fall-back to
    the full-logits path would resurface as an opaque multi-GB OOM in
    exactly the configs the flag exists to rescue."""
    import jax
    import pytest

    from pccl_tpu.models import gpt

    cfg = gpt.tiny_config()
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    tok = jax.random.randint(jax.random.PRNGKey(1), (1, cfg.block_size), 0,
                             cfg.vocab_size)
    with pytest.raises(ValueError, match="must divide"):
        gpt.loss_fn(params, tok, tok, cfg, None, False, 100)


def test_grad_accumulation_matches_full_batch():
    """accum_steps=A over [A, B, T] must match one step over [A·B, T]:
    CE is a per-sequence mean, so the average of A microbatch means (and
    grads) equals the full-batch mean exactly — same updated params, same
    loss, up to fp32 reduction order."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from pccl_tpu.models import gpt
    from pccl_tpu.parallel import mesh as mesh_lib, train as train_lib

    import optax

    cfg = gpt.tiny_config()
    mesh = mesh_lib.make_mesh(jax.devices()[:2], ("dp", "tp"))
    tok = np.asarray(jax.random.randint(
        jax.random.PRNGKey(2), (4, cfg.block_size), 0, cfg.vocab_size))

    def run(accum):
        params, _, _ = train_lib.make_train_state(
            jax.random.PRNGKey(0), cfg, mesh)
        # plain SGD(1.0): new_params − old_params == −grads, so the
        # comparison is of the accumulated GRADIENTS themselves (AdamW's
        # m/√v would sign-normalize noise-level grads and amplify bf16
        # reduction-order dust into lr-scale diffs)
        tx = optax.sgd(1.0)
        opt = tx.init(params)
        step = train_lib.build_train_step(cfg, tx, mesh, accum_steps=accum)
        t = jnp.asarray(tok.reshape(2, 2, -1) if accum > 1 else tok)
        return step(params, opt, t, t)

    p1, _, l1 = run(1)
    p2, _, l2 = run(2)
    np.testing.assert_allclose(float(l1), float(l2), rtol=2e-5)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=2e-2, atol=5e-5), p1, p2)
