"""Expert-parallel MoE: EP dispatch matches the dense reference."""

from __future__ import annotations

import numpy as np
import pytest


def _setup(eight_devices, S, E=4, B=4, T=16, d=32, f=64):
    import jax

    from pccl_tpu.ops import moe
    from pccl_tpu.parallel import mesh as mesh_lib

    mesh = mesh_lib.make_mesh(eight_devices[:S], ("ep",), (S,))
    params = moe.init_moe_params(jax.random.PRNGKey(0), d, f, E)
    x = jax.random.normal(jax.random.PRNGKey(1), (B, T, d), jax.numpy.float32)
    return mesh, params, x


@pytest.mark.parametrize("S,E", [(2, 4), (4, 4), (4, 8)])
def test_moe_ep_matches_dense(eight_devices, S, E):
    import jax

    from pccl_tpu.ops import moe

    mesh, params, x = _setup(eight_devices, S, E=E)
    # ample capacity: no token drops, so EP must match dense exactly
    dense = moe.moe_mlp_dense(x, params, capacity_factor=float(E))
    sharded = moe.shard_moe_params(params, mesh)
    from jax.sharding import NamedSharding, PartitionSpec as P

    x_ep = jax.device_put(x, NamedSharding(mesh, P("ep")))
    ep = jax.jit(lambda xx, pp: moe.moe_mlp_ep(
        xx, pp, mesh, capacity_factor=float(E)))(x_ep, sharded)
    np.testing.assert_allclose(np.asarray(ep), np.asarray(dense),
                               rtol=2e-2, atol=2e-2)  # bf16 expert compute


def test_moe_grad_flows(eight_devices):
    import jax
    import jax.numpy as jnp

    from pccl_tpu.ops import moe

    mesh, params, x = _setup(eight_devices, 2, E=4, B=2, T=8)
    sharded = moe.shard_moe_params(params, mesh)

    def loss(p, xx):
        return jnp.sum(moe.moe_mlp_ep(xx, p, mesh,
                                      capacity_factor=4.0) ** 2)

    g = jax.jit(jax.grad(loss))(sharded, x)
    # expert weights that received tokens must have nonzero grads
    assert float(jnp.abs(g["w_in"]).sum()) > 0
    assert float(jnp.abs(g["gate"]).sum()) > 0


def test_moe_capacity_drops_tokens(eight_devices):
    """With capacity 0-ish, dropped tokens produce zero output (switch
    semantics), not garbage — on BOTH the dense and the EP path."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from pccl_tpu.ops import moe

    mesh, params, x = _setup(eight_devices, 2, E=4, B=2, T=8)
    out = moe.moe_mlp_dense(x, params, capacity_factor=0.01)  # C=1
    arr = np.asarray(out)
    assert np.isfinite(arr).all()
    # most tokens dropped -> mostly zero rows
    zero_rows = (np.abs(arr).sum(axis=-1) == 0).mean()
    assert zero_rows > 0.5

    sharded = moe.shard_moe_params(params, mesh)
    x_ep = jax.device_put(x, NamedSharding(mesh, P("ep")))
    out_ep = jax.jit(lambda xx, pp: moe.moe_mlp_ep(
        xx, pp, mesh, capacity_factor=0.01))(x_ep, sharded)
    arr_ep = np.asarray(out_ep)
    assert np.isfinite(arr_ep).all()
    # per-shard capacity keeps up to S*C tokens globally (C per shard per
    # expert), so the drop fraction bound is weaker than dense
    assert (np.abs(arr_ep).sum(axis=-1) == 0).mean() >= 0.5
