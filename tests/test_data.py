"""Token dataset sharding + device prefetch pipeline."""

import numpy as np
import pytest

from pccl_tpu.utils.data import TokenDataset, prefetch_to_device


def _toks(n=4096, seed=0):
    return np.random.default_rng(seed).integers(0, 256, size=n).astype(np.uint16)


def test_batches_are_next_token_pairs():
    ds = TokenDataset(_toks(), block_size=32, batch_size=4, seed=1)
    x, y = ds.sample()
    assert x.shape == y.shape == (4, 32) and x.dtype == np.int32
    # y is x shifted by one within the source stream
    toks = ds.tokens
    for row_x, row_y in zip(x, y):
        s = np.where((toks[:-33] == row_x[0]))[0]
        assert row_y[0] == row_x[1] or any(
            np.array_equal(toks[i + 1:i + 33], row_y) for i in s)


def test_streams_deterministic_and_disjoint_by_worker():
    mk = lambda w: TokenDataset(_toks(), 16, 8, seed=7, worker_index=w)
    a1, a2, b = mk(0), mk(0), mk(1)
    xa1, _ = a1.sample()
    xa2, _ = a2.sample()
    xb, _ = b.sample()
    np.testing.assert_array_equal(xa1, xa2)  # same (seed, worker): identical
    assert not np.array_equal(xa1, xb)       # different worker: different crops


def test_memmap_backed(tmp_path):
    toks = _toks(8192)
    f = tmp_path / "toks.bin"
    toks.tofile(f)
    mm = np.memmap(f, dtype=np.uint16, mode="r")
    ds = TokenDataset(mm, 64, 2, seed=3)
    x, y = ds.sample()
    assert x.shape == (2, 64)
    np.testing.assert_array_equal(x[:, 1:], y[:, :-1])


def test_prefetch_matches_direct_iteration():
    import itertools

    import jax

    ds = TokenDataset(_toks(), 16, 4, seed=9)
    ref = TokenDataset(_toks(), 16, 4, seed=9)  # same stream, sampled directly
    direct = [ref.sample() for _ in range(5)]
    staged = list(itertools.islice(prefetch_to_device(iter(ds)), 5))
    for (dx, dy), st in zip(direct, staged):
        sx, sy = st
        assert isinstance(sx, jax.Array)
        np.testing.assert_array_equal(np.asarray(sx), dx)
        np.testing.assert_array_equal(np.asarray(sy), dy)


def test_prefetch_with_sharding(eight_devices):
    import itertools

    from jax.sharding import NamedSharding, PartitionSpec as P

    from pccl_tpu.parallel import mesh as mesh_lib

    mesh = mesh_lib.make_mesh(eight_devices, ("dp",), (8,))
    sh = NamedSharding(mesh, P("dp", None))
    ds = TokenDataset(_toks(), 16, 8, seed=4)
    for x, y in itertools.islice(prefetch_to_device(iter(ds), sharding=sh), 3):
        assert x.sharding.is_equivalent_to(sh, 2)
        assert x.shape == (8, 16)


def test_prefetch_propagates_iterator_errors():
    def bad():
        yield np.zeros((2, 2), np.int32)
        raise RuntimeError("source died")

    it = prefetch_to_device(bad())
    next(it)
    with pytest.raises(RuntimeError, match="source died"):
        next(it)


def test_prefetch_finite_source_terminates():
    src = [np.full((1,), i, np.int32) for i in range(4)]
    got = [int(np.asarray(a)[0]) for a in prefetch_to_device(iter(src))]
    assert got == [0, 1, 2, 3]
